"""``python -m tpudash.chaos`` — one-command chaos drills.

Two drills live here:

**The breaker drill** (default, no arguments): serves the full dashboard
over a 3-endpoint MultiSource of synthetic slices, each wrapped in
ChaosSource, so every resilience layer is visible live on one laptop:
per-endpoint circuit breakers opening and reclosing (watch ``/healthz``
→ ``source_health.endpoints``), the ``endpoint_down`` alert on the
banner, partial-degradation warnings while the healthy slices keep
rendering, and concurrent child fetches keeping the frame fast while one
endpoint misbehaves.

    python -m tpudash.chaos                      # the default drill
    TPUDASH_CHAOS='flap:period=4' python -m tpudash.chaos   # your scenario

The default drill: endpoint ``chaos-a`` healthy, ``chaos-b`` flapping
(period 6 — watch its breaker open and reclose), ``chaos-c`` slow and
lossy (latency + transient errors + one dropped chip).  A custom
``TPUDASH_CHAOS`` scenario replaces the per-endpoint defaults and is
applied to endpoints ``chaos-b`` and ``chaos-c`` (``chaos-a`` stays
healthy as the control, so the page always renders something).

**The overload drill** (``python -m tpudash.chaos overload``): a
client-swarm soak against the SERVING side's overload protection
(tpudash.app.overload).  It boots the dashboard in-process over a
chaos-latency synthetic source with aggressive shedding knobs, then
drives N concurrent synthetic clients over ``/api/frame``,
``/api/stream``, and ``/api/select`` — including deliberately-stalled
SSE consumers — and asserts the overload contract end to end:

- excess requests shed with ``503`` + ``Retry-After``;
- ``GET /api/frame`` degrades to the last published frame with
  ``stale: true`` instead of erroring;
- slow consumers blocking an SSE write past
  ``TPUDASH_SSE_WRITE_DEADLINE`` are evicted;
- ``/healthz`` keeps answering in under a second throughout;
- zero unhandled exceptions in the server logs;
- shed/evict counters visible in ``/api/timings``.

    python -m tpudash.chaos overload --clients 100 --seconds 10

Exit status 0 = every invariant held; 1 = the printed JSON names what
didn't.  CI runs this on every PR (chaos-soak job).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import sys
import time

from tpudash.config import Config, configure_logging, env_is_set, load_config

log = logging.getLogger(__name__)

#: per-endpoint default scenarios (label → TPUDASH_CHAOS grammar)
DEFAULT_DRILL = {
    "chaos-a": "",
    "chaos-b": "flap:period=6;seed=1",
    "chaos-c": (
        "latency:p=0.5,ms=300;error:p=0.25;"
        "drop_chip:slice=chaos-c,chip=3;seed=2"
    ),
}

#: the overload drill's source scenario: every fetch pays dispersed
#: latency, so refreshes are slow and requests genuinely pile up behind
#: the frame lock (jittered so the pileup isn't metronomic)
OVERLOAD_SCENARIO = "latency:p=0.8,ms=200,jitter=150;seed=7"

#: drill knobs applied unless the operator set the env var — aggressive
#: enough that a 100-client swarm visibly sheds within seconds
_OVERLOAD_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_REFRESH_WATCHDOG": ("refresh_watchdog", 2.0),
    "TPUDASH_MAX_CONCURRENCY": ("max_concurrency", 16),
    "TPUDASH_RATE_LIMIT": ("rate_limit", 2.0),
    "TPUDASH_RATE_BURST": ("rate_burst", 4.0),
    "TPUDASH_MAX_STREAMS": ("max_streams", 24),
    "TPUDASH_SSE_WRITE_DEADLINE": ("sse_write_deadline", 1.0),
    "TPUDASH_SHED_RETRY_AFTER": ("shed_retry_after", 1.0),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 128),
}


def chaos_demo_source(cfg: Config):
    """The drill's MultiSource: three synthetic slices behind chaos."""
    from tpudash.sources.chaos import ChaosSource
    from tpudash.sources.fixture import SyntheticSource
    from tpudash.sources.multi import EndpointSpec, MultiSource

    # the registry already mapped TPUDASH_CHAOS → cfg.chaos (load_config);
    # the drill reuses it as the per-endpoint scenario override
    override = cfg.chaos
    children = []
    for label, default_spec in DEFAULT_DRILL.items():
        spec = default_spec
        if override and label != "chaos-a":
            spec = override
        inner = SyntheticSource(
            num_chips=min(cfg.synthetic_chips, 64),
            generation=cfg.generation,
        )
        src = ChaosSource(inner, spec) if spec else inner
        children.append(
            (EndpointSpec(url=f"synthetic://{label}", slice_name=label), src)
        )
    return MultiSource(cfg, children=children)


def make_chaos_app(cfg: Config | None = None):
    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService

    cfg = cfg or load_config()
    # short breaker cooldown + tight deadline so the drill's state
    # transitions are watchable within a coffee's attention span (env
    # overrides still win — load_config already applied them)
    if not env_is_set("TPUDASH_BREAKER_COOLDOWN"):
        cfg = dataclasses.replace(cfg, breaker_cooldown=10.0)
    if not env_is_set("TPUDASH_MULTI_DEADLINE"):
        cfg = dataclasses.replace(cfg, multi_deadline=1.0)
    service = DashboardService(cfg, chaos_demo_source(cfg))
    return DashboardServer(service).build_app(), cfg


# ---------------------------------------------------------------------------
# Overload drill — a client swarm against the admission/shedding layer.
# ---------------------------------------------------------------------------


def make_overload_server(cfg: Config | None = None):
    """(DashboardServer, cfg) under drill knobs: a chaos-latency synthetic
    source plus shedding limits a 100-client swarm will actually hit.
    Explicit env settings win over every drill default."""
    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService
    from tpudash.sources.chaos import ChaosSource
    from tpudash.sources.fixture import SyntheticSource

    cfg = cfg or load_config()
    for env_name, (field, value) in _OVERLOAD_KNOBS.items():
        if not env_is_set(env_name):
            cfg = dataclasses.replace(cfg, **{field: value})
    inner = SyntheticSource(
        num_chips=min(cfg.synthetic_chips, 128), generation=cfg.generation
    )
    source = ChaosSource(inner, cfg.chaos or OVERLOAD_SCENARIO)
    return DashboardServer(DashboardService(cfg, source)), cfg


class _ErrorTrap(logging.Handler):
    """Collects ERROR+ records — the drill's "zero unhandled exceptions
    in server logs" check reads these (aiohttp logs every handler
    traceback as ERROR on 'aiohttp.server')."""

    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.records: list = []

    def emit(self, record: logging.LogRecord) -> None:
        self.records.append(self.format(record))


async def _stalled_stream(host: str, port: int, sid: str, stop: asyncio.Event):
    """A deliberately-slow SSE consumer: tiny receive buffer, reads a few
    KB of the first event, then stops draining entirely — the shape of a
    wedged dashboard tab the write deadline must evict."""
    import socket as socketmod

    sock = socketmod.socket(socketmod.AF_INET, socketmod.SOCK_STREAM)
    sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_RCVBUF, 4096)
    sock.setblocking(False)
    loop = asyncio.get_running_loop()
    writer = None
    try:
        await loop.sock_connect(sock, (host, port))
        # limit=2048: asyncio's default StreamReader otherwise buffers
        # ~128KB in user space before pausing the transport — the "slow"
        # consumer would silently absorb many events instead of stalling
        reader, writer = await asyncio.open_connection(sock=sock, limit=2048)
        writer.write(
            (
                f"GET /api/stream HTTP/1.0\r\nHost: {host}\r\n"
                f"Cookie: tpudash_sid={sid}\r\n\r\n"
            ).encode()
        )
        await writer.drain()
        await asyncio.wait_for(reader.read(2048), timeout=10)  # first bytes
        await stop.wait()  # ...then never drain again
    except (OSError, asyncio.TimeoutError):
        pass  # the server evicting us closes the pipe — expected
    finally:
        if writer is not None:
            writer.close()
        else:
            sock.close()


async def run_overload_drill(
    clients: int = 100, seconds: float = 10.0, cfg: Config | None = None
) -> dict:
    """Drive the swarm; return a JSON-able summary with ``ok`` and the
    list of violated invariants (empty when the drill passes)."""
    from aiohttp import ClientSession, web

    # constructed in the executor: DashboardService.__init__ does real
    # file I/O (state checkpoint, history restore/sweep) and sources own
    # HTTP sessions — none of it belongs on the loop the drill is about
    # to measure (asynccheck rule ``async-blocking``)
    loop = asyncio.get_running_loop()
    server, cfg = await loop.run_in_executor(None, make_overload_server, cfg)
    app = server.build_app()

    # Small per-connection output buffers on the stream route ONLY inside
    # the drill: localhost sockets otherwise absorb megabytes, and the
    # point is to prove eviction, not to wait out kernel buffers.
    import socket as socketmod

    async def _tiny_stream_buffers(request, response):
        if request.path != "/api/stream" or request.transport is None:
            return
        sock = request.transport.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_SNDBUF, 8192)
        request.transport.set_write_buffer_limits(high=8192)

    app.on_response_prepare.append(_tiny_stream_buffers)

    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    host, port = runner.addresses[0][:2]
    base = f"http://{host}:{port}"

    stop = asyncio.Event()
    stats = {
        "ok_200": 0,
        "not_modified_304": 0,
        "shed_503": 0,
        "shed_with_retry_after": 0,
        "stale_frames": 0,
        "select_ok": 0,
        "stream_events": 0,
        "healthz_probes": 0,
        "healthz_failures": 0,
        "healthz_max_ms": 0.0,
    }

    from aiohttp import ClientError

    async def hammer(session: ClientSession, sid: str):
        cookies = {"tpudash_sid": sid}
        while not stop.is_set():
            try:
                async with session.get(
                    f"{base}/api/frame", cookies=cookies
                ) as r:
                    if r.status == 200:
                        body = await r.json()
                        if body.get("stale"):
                            stats["stale_frames"] += 1
                        else:
                            stats["ok_200"] += 1
                    elif r.status == 304:
                        stats["not_modified_304"] += 1
                    elif r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
                async with session.post(
                    f"{base}/api/select",
                    json={"toggle": "slice-0/1"},
                    cookies=cookies,
                ) as r:
                    if r.status == 200:
                        stats["select_ok"] += 1
                    elif r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
            except (OSError, ClientError):
                # a shed/reset/server-closed connection is the drill
                # working — the hammer client must keep hammering, not
                # die and silently thin the swarm (ClientError covers
                # aiohttp spellings like ServerDisconnectedError that
                # are NOT OSError subclasses)
                pass
            await asyncio.sleep(0)

    async def stream_reader(session: ClientSession, sid: str):
        try:
            async with session.get(
                f"{base}/api/stream", cookies={"tpudash_sid": sid}
            ) as r:
                if r.status == 503:
                    stats["shed_503"] += 1
                    if r.headers.get("Retry-After"):
                        stats["shed_with_retry_after"] += 1
                    return
                async for _line in r.content:
                    stats["stream_events"] += 1
                    if stop.is_set():
                        return
        except (OSError, ClientError, asyncio.TimeoutError):
            pass

    async def healthz_probe(session: ClientSession):
        # every probe is bounded and every failure is RECORDED: a hung
        # /healthz must fail the drill's <1s invariant, not block this
        # coroutine until teardown with healthz_max_ms frozen at its
        # last good value
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                async def probe():
                    async with session.get(f"{base}/healthz") as r:
                        await r.json()
                        return r.status

                status = await asyncio.wait_for(probe(), timeout=1.0)
                if status != 200:
                    stats["healthz_failures"] += 1
                ms = (time.monotonic() - t0) * 1e3
                stats["healthz_max_ms"] = max(stats["healthz_max_ms"], ms)
            except asyncio.TimeoutError:
                stats["healthz_failures"] += 1
                stats["healthz_max_ms"] = max(
                    stats["healthz_max_ms"], 1000.0
                )
            except (OSError, ClientError):
                stats["healthz_failures"] += 1
            stats["healthz_probes"] += 1
            await asyncio.sleep(0.25)

    # role split that stays sane at any --clients value: stalled and
    # stream roles never eat the whole budget, and at least one hammer
    # client always exists (without hammerers nothing sheds and the
    # drill would fail with a misleading "no sheds observed")
    clients = max(4, clients)
    n_stalled = min(max(2, clients // 20), clients // 4)
    n_streams = min(max(4, clients // 5), clients // 2)
    n_hammer = max(1, clients - n_stalled - n_streams)
    async with ClientSession() as session:
        # stalled consumers pre-select everything so their frames are big
        # enough to fill the (shrunken) buffers within a tick or two
        for i in range(n_stalled):
            try:
                await session.post(
                    f"{base}/api/select",
                    json={"all": True},
                    cookies={"tpudash_sid": f"stall-{i}"},
                )
            except OSError:
                pass
        # Phase A — attach the streams (including the stalled consumers)
        # and let them receive their first event BEFORE the hammer storm:
        # a slow consumer in the wild is a tab that attached while things
        # were calm and then wedged, and the warmup keeps the eviction
        # proof from racing 100 hammer clients for the frame lock.
        # Every spawn below is RETAINED in `tasks` (awaited, then
        # cancelled at teardown) — the asynccheck ``unretained-task``
        # rule holds this file to that.
        tasks = [
            asyncio.ensure_future(healthz_probe(session)),
            *(
                asyncio.ensure_future(
                    _stalled_stream(host, port, f"stall-{i}", stop)
                )
                for i in range(n_stalled)
            ),
            *(
                asyncio.ensure_future(
                    stream_reader(session, f"swarm-{i}")
                )
                for i in range(n_streams)
            ),
        ]
        await asyncio.sleep(min(3.0, max(1.0, seconds / 3.0)))
        # Phase B — the swarm
        tasks += [
            asyncio.ensure_future(hammer(session, f"swarm-{i}"))
            for i in range(n_hammer)
        ]
        await asyncio.sleep(seconds)
        stop.set()
        await asyncio.wait(tasks, timeout=10)
        for t in tasks:
            t.cancel()
        # /healthz and /api/timings still answer after the storm, and the
        # counters the runbook points at are actually there
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
        async with session.get(f"{base}/api/timings") as r:
            timings = await r.json()
    await runner.cleanup()
    logging.getLogger().removeHandler(trap)

    snap = server.overload.snapshot()
    failures = []
    if stats["shed_503"] == 0 or stats["shed_with_retry_after"] == 0:
        failures.append("no 503+Retry-After sheds observed")
    if stats["stale_frames"] == 0:
        failures.append("no stale:true degraded frames served")
    if snap["counters"]["evicted_slow_consumers"] == 0:
        failures.append("no slow consumers evicted by the write deadline")
    if stats["healthz_max_ms"] >= 1000.0 or stats["healthz_failures"] > 0:
        failures.append(
            f"healthz degraded: max {stats['healthz_max_ms']:.0f}ms, "
            f"{stats['healthz_failures']} failed/hung probe(s)"
        )
    if "overload" not in timings or "counters" not in timings["overload"]:
        failures.append("/api/timings lost the overload counters")
    # the loop-lag sanitizer must be live AND flat: overload protection
    # that holds while the event loop starves is no protection at all.
    # p50 (not max) is the assertion — a single GC pause or laggy CI tick
    # must not flake the drill, a *sustained* stall must fail it.
    lag = timings.get("loop_lag_ms") or {}
    if not lag.get("samples"):
        failures.append("loop-lag monitor recorded no heartbeat samples")
    elif lag.get("p50") is not None and lag["p50"] >= cfg.loop_lag_budget:
        failures.append(
            f"event-loop lag not flat: p50 {lag['p50']}ms >= "
            f"{cfg.loop_lag_budget:g}ms budget "
            f"({lag.get('slow_callbacks', 0)} slow callback(s))"
        )
    if health.get("ok") is not True:
        failures.append("healthz ok flapped under load")
    if trap.records:
        failures.append(
            f"{len(trap.records)} unhandled server exception(s): "
            + trap.records[0][:500]
        )
    return {
        "ok": not failures,
        "failures": failures,
        "clients": clients,
        "seconds": seconds,
        "requests": stats,
        "overload": snap,
        "loop_lag_ms": lag,
        "healthz_status": health.get("status"),
        "limits": snap["limits"],
    }


def main(argv: "list[str] | None" = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tpudash.chaos",
        description="chaos drills (default: live breaker drill server)",
    )
    sub = parser.add_subparsers(dest="mode")
    ov = sub.add_parser(
        "overload", help="client-swarm overload/load-shedding soak"
    )
    ov.add_argument("--clients", type=int, default=100)
    ov.add_argument("--seconds", type=float, default=10.0)
    args = parser.parse_args(argv)

    configure_logging()
    if args.mode == "overload":
        summary = asyncio.run(
            run_overload_drill(clients=args.clients, seconds=args.seconds)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)

    from aiohttp import web

    app, cfg = make_chaos_app()
    log.info(
        "chaos drill on :%d — endpoints %s; watch /healthz "
        "source_health.endpoints for breaker transitions",
        cfg.port,
        ", ".join(DEFAULT_DRILL),
    )
    web.run_app(app, host=cfg.host, port=cfg.port)  # pragma: no cover


if __name__ == "__main__":  # pragma: no cover
    main()
