"""``python -m tpudash.chaos`` — a one-command chaos drill.

Serves the full dashboard over a 3-endpoint MultiSource of synthetic
slices, each wrapped in ChaosSource, so every resilience layer is
visible live on one laptop: per-endpoint circuit breakers opening and
reclosing (watch ``/healthz`` → ``source_health.endpoints``), the
``endpoint_down`` alert on the banner, partial-degradation warnings
while the healthy slices keep rendering, and concurrent child fetches
keeping the frame fast while one endpoint misbehaves.

    python -m tpudash.chaos                      # the default drill
    TPUDASH_CHAOS='flap:period=4' python -m tpudash.chaos   # your scenario

The default drill: endpoint ``chaos-a`` healthy, ``chaos-b`` flapping
(period 6 — watch its breaker open and reclose), ``chaos-c`` slow and
lossy (latency + transient errors + one dropped chip).  A custom
``TPUDASH_CHAOS`` scenario replaces the per-endpoint defaults and is
applied to endpoints ``chaos-b`` and ``chaos-c`` (``chaos-a`` stays
healthy as the control, so the page always renders something).
"""

from __future__ import annotations

import logging

from tpudash.config import Config, configure_logging, env_is_set, load_config

log = logging.getLogger(__name__)

#: per-endpoint default scenarios (label → TPUDASH_CHAOS grammar)
DEFAULT_DRILL = {
    "chaos-a": "",
    "chaos-b": "flap:period=6;seed=1",
    "chaos-c": (
        "latency:p=0.5,ms=300;error:p=0.25;"
        "drop_chip:slice=chaos-c,chip=3;seed=2"
    ),
}


def chaos_demo_source(cfg: Config):
    """The drill's MultiSource: three synthetic slices behind chaos."""
    from tpudash.sources.chaos import ChaosSource
    from tpudash.sources.fixture import SyntheticSource
    from tpudash.sources.multi import EndpointSpec, MultiSource

    # the registry already mapped TPUDASH_CHAOS → cfg.chaos (load_config);
    # the drill reuses it as the per-endpoint scenario override
    override = cfg.chaos
    children = []
    for label, default_spec in DEFAULT_DRILL.items():
        spec = default_spec
        if override and label != "chaos-a":
            spec = override
        inner = SyntheticSource(
            num_chips=min(cfg.synthetic_chips, 64),
            generation=cfg.generation,
        )
        src = ChaosSource(inner, spec) if spec else inner
        children.append(
            (EndpointSpec(url=f"synthetic://{label}", slice_name=label), src)
        )
    return MultiSource(cfg, children=children)


def make_chaos_app(cfg: Config | None = None):
    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService

    cfg = cfg or load_config()
    # short breaker cooldown + tight deadline so the drill's state
    # transitions are watchable within a coffee's attention span (env
    # overrides still win — load_config already applied them)
    if not env_is_set("TPUDASH_BREAKER_COOLDOWN"):
        import dataclasses

        cfg = dataclasses.replace(cfg, breaker_cooldown=10.0)
    if not env_is_set("TPUDASH_MULTI_DEADLINE"):
        import dataclasses

        cfg = dataclasses.replace(cfg, multi_deadline=1.0)
    service = DashboardService(cfg, chaos_demo_source(cfg))
    return DashboardServer(service).build_app(), cfg


def main() -> None:  # pragma: no cover - blocking entry
    from aiohttp import web

    configure_logging()
    app, cfg = make_chaos_app()
    log.info(
        "chaos drill on :%d — endpoints %s; watch /healthz "
        "source_health.endpoints for breaker transitions",
        cfg.port,
        ", ".join(DEFAULT_DRILL),
    )
    web.run_app(app, host=cfg.host, port=cfg.port)


if __name__ == "__main__":  # pragma: no cover
    main()
