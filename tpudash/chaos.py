"""``python -m tpudash.chaos`` — one-command chaos drills.

Two drills live here:

**The breaker drill** (default, no arguments): serves the full dashboard
over a 3-endpoint MultiSource of synthetic slices, each wrapped in
ChaosSource, so every resilience layer is visible live on one laptop:
per-endpoint circuit breakers opening and reclosing (watch ``/healthz``
→ ``source_health.endpoints``), the ``endpoint_down`` alert on the
banner, partial-degradation warnings while the healthy slices keep
rendering, and concurrent child fetches keeping the frame fast while one
endpoint misbehaves.

    python -m tpudash.chaos                      # the default drill
    TPUDASH_CHAOS='flap:period=4' python -m tpudash.chaos   # your scenario

The default drill: endpoint ``chaos-a`` healthy, ``chaos-b`` flapping
(period 6 — watch its breaker open and reclose), ``chaos-c`` slow and
lossy (latency + transient errors + one dropped chip).  A custom
``TPUDASH_CHAOS`` scenario replaces the per-endpoint defaults and is
applied to endpoints ``chaos-b`` and ``chaos-c`` (``chaos-a`` stays
healthy as the control, so the page always renders something).

**The overload drill** (``python -m tpudash.chaos overload``): a
client-swarm soak against the SERVING side's overload protection
(tpudash.app.overload).  It boots the dashboard in-process over a
chaos-latency synthetic source with aggressive shedding knobs, then
drives N concurrent synthetic clients over ``/api/frame``,
``/api/stream``, and ``/api/select`` — including deliberately-stalled
SSE consumers — and asserts the overload contract end to end:

- excess requests shed with ``503`` + ``Retry-After``;
- ``GET /api/frame`` degrades to the last published frame with
  ``stale: true`` instead of erroring;
- slow consumers blocking an SSE write past
  ``TPUDASH_SSE_WRITE_DEADLINE`` are evicted;
- ``/healthz`` keeps answering in under a second throughout;
- zero unhandled exceptions in the server logs;
- shed/evict counters visible in ``/api/timings``.

    python -m tpudash.chaos overload --clients 100 --seconds 10

**The storm drill** (``python -m tpudash.chaos storm``): the broadcast
plane's soak (tpudash.broadcast).  It boots the REAL supervised tier —
one compose process publishing sealed cohort buffers on the frame bus
plus N SO_REUSEPORT fan-out worker processes — then drives a 1000-client
SSE storm (including deliberately-stalled consumers) at the shared public
port and asserts the overload contract holds in every process:

- the storm spreads across >= 2 distinct worker pids;
- per-worker stream caps shed overflow with ``503`` + ``Retry-After``;
- stalled consumers are evicted by each worker's write deadline;
- ``loop_lag_ms`` p50 stays under budget in the compose process AND
  every worker (each reports its own monitor on ``/healthz``);
- zero unhandled exceptions in any process's captured logs;
- ``/healthz`` keeps answering throughout (zero failed probes, p50
  under a second — probed from a dedicated thread so the drill's own
  1000-task client loop can't pollute the measurement).

    python -m tpudash.chaos storm --clients 1000 --workers 2 --seconds 30

**The killall drill** (``python -m tpudash.chaos killall``): the
crash-anything soak.  It boots the PROCESS-TREE supervised tier
(TierSupervisor: compose child + N workers, persistent tsdb + state)
and then kills things, in sequence, mid-storm:

- SIGKILL the COMPOSE process: workers keep serving ``/api/frame``
  (``stale: true`` + a synthesized ``compose_down`` alert) and
  ``/api/stream`` from their bus mirrors, ``/healthz`` reports
  ``compose_down`` from any worker, NO worker exits, and a mid-outage
  ``Last-Event-ID`` reconnect resumes with a DELTA from the retained
  seal windows; the restarted compose reloads the tsdb + state, bumps
  the seal-seq epoch, and re-snapshots every worker over the bus;
- SIGKILL a WORKER: the supervisor restarts it (exit code + restart
  stamp journaled, visible on ``/api/workers``) while the public port
  keeps answering;
- SIGKILL a store process MID-SNAPSHOT (twice): every snapshot dir then
  either restores completely or is REFUSED by manifest/CRC validation —
  never a silently partial store;
- follower catch-up: a read-only standby tails a live leader whose tiny
  retention reclaims segments under it, converges with everything the
  leader still holds, and reports bounded replication lag.

    python -m tpudash.chaos killall --clients 24 --workers 2

**The partition drill** (``python -m tpudash.chaos partition``): fleet
federation (tpudash.federation) under network partitions.  It boots N
child dashboards plus a federated parent, then partitions K of them
mid-storm — one connect-refused, one accept-then-hang, one
slow-drip — and later flaps one at sub-dwell period, asserting the
degrade-per-child contract:

- the parent's ``/api/frame`` keeps serving with EXACTLY the affected
  children marked stale (measured ``staleness_s``), their last-good
  chips still on the pane, and ``partial: true``;
- past the stale budget the affected children go dark and their chips
  drop — the frame still serves the healthy remainder;
- ``child_down`` fires per affected child, ``fleet_partial`` beside it,
  and the anti-flap dwell keeps a flapping child from resolve-flapping
  the pager;
- ``/healthz`` stays ``ok: true`` with truthful per-child status, the
  fleet SSE stream keeps ticking, steady-state summary polls hit the
  ETag/304 path, and recovery lands within one poll of heal;
- zero unhandled exceptions throughout.

    python -m tpudash.chaos partition --children 4

**The edgestorm drill** (``python -m tpudash.chaos edgestorm``): the
edge delivery tier (tpudash.broadcast.edge) under kills and
partitions.  It boots a REAL single-process compose publishing the
frame bus over TCP (token-authenticated) plus N real edge
subprocesses — each dialing the bus through a drill-owned TCP
forwarder, the partition switch — and a streaming client population
spread across the edges, then breaks things mid-storm:

- SIGKILL an EDGE: its clients fail over to another edge and
  ``Last-Event-ID`` resumes with a DELTA — seal event ids are global
  (``<cid>-<seq>``, epoch-floored), so any edge's mirror window can
  continue any other edge's chain;
- PARTITION an edge's bus link (blackhole, then connect-refused): the
  edge detects the silent link by heartbeat budget, serves stale
  frames with a ``compose_down`` alert while ``/healthz`` stays
  ``ok: true``, and heals within ONE reconnect of the forwarder
  returning;
- SIGKILL the COMPOSE process: every edge degrades in lockstep
  (stale + alert, none dark); the restarted compose bumps the seal
  epoch so resumed seqs can never alias, and every edge resyncs via
  snapshot-then-stream;
- throughout: ZERO sequence-gap resyncs on healthy links and zero
  unhandled exceptions in any process's captured logs.

    python -m tpudash.chaos edgestorm --edges 16 --clients 256

**The coldstorm drill** (``python -m tpudash.chaos coldstorm``): the
cold archive tier (tpudash.tsdb.cold/compact/objstore) under every
failure the object store can throw:

- SIGKILL a store+compactor process mid-upload (twice): every object
  left behind is a complete digest-verified bundle or an ignorable
  husk, NO segment was reclaimed without a verified bundle naming it
  as a source, and a cold reopen serves one contiguous hot→cold
  timeline — zero duplicates, zero gaps;
- torn uploads (injected fault): read-back verification catches the
  tear, the compactor retries under its deadline and deletes what it
  refused — the store converges to verified bundles with no husks;
- a bit-rotted bundle (bytes flipped AFTER its upload verified): the
  serving tier catches it at download, quarantines it with a
  persistent marker and a ``cold_corrupt`` page naming the bundle,
  and keeps serving the intact bundles — corrupt data is never served;
- a DARK object store, through a real HTTP dashboard: ``/api/range``
  degrades to the hot horizon with ``partial: true``, the
  ``cold_unreachable`` alert pages, ``/healthz`` stays ``ok: true``
  with a truthful status (a restart fixes nothing) — and restoring
  the store heals all of it with no operator action;
- a 90-day-old incident whose raw AND rollup tiers fully expired,
  replayed through the real ``anomaly replay --tsdb`` CLI from the
  archives alone.

    python -m tpudash.chaos coldstorm --kills 2

Exit status 0 = every invariant held; 1 = the printed JSON names what
didn't.  CI runs the overload, storm, killall, partition, edgestorm,
and coldstorm drills on every PR (chaos-soak job).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import functools
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time

from tpudash import schema
from tpudash.analysis.leakcheck import process_census, warm_default_executor
from tpudash.config import Config, configure_logging, env_is_set, load_config

log = logging.getLogger(__name__)

#: per-endpoint default scenarios (label → TPUDASH_CHAOS grammar)
DEFAULT_DRILL = {
    "chaos-a": "",
    "chaos-b": "flap:period=6;seed=1",
    "chaos-c": (
        "latency:p=0.5,ms=300;error:p=0.25;"
        "drop_chip:slice=chaos-c,chip=3;seed=2"
    ),
}

#: the overload drill's source scenario: every fetch pays dispersed
#: latency, so refreshes are slow and requests genuinely pile up behind
#: the frame lock (jittered so the pileup isn't metronomic)
OVERLOAD_SCENARIO = "latency:p=0.8,ms=200,jitter=150;seed=7"

#: drill knobs applied unless the operator set the env var — aggressive
#: enough that a 100-client swarm visibly sheds within seconds
_OVERLOAD_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_REFRESH_WATCHDOG": ("refresh_watchdog", 2.0),
    "TPUDASH_MAX_CONCURRENCY": ("max_concurrency", 16),
    "TPUDASH_RATE_LIMIT": ("rate_limit", 2.0),
    "TPUDASH_RATE_BURST": ("rate_burst", 4.0),
    "TPUDASH_MAX_STREAMS": ("max_streams", 24),
    "TPUDASH_SSE_WRITE_DEADLINE": ("sse_write_deadline", 1.0),
    "TPUDASH_SHED_RETRY_AFTER": ("shed_retry_after", 1.0),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 128),
    # small per-stream output buffers: localhost sockets otherwise absorb
    # megabytes and the drill is here to prove eviction, not to wait out
    # kernel buffers (this is the production knob, not a test hook)
    "TPUDASH_SSE_SNDBUF": ("sse_sndbuf", 8192),
}

#: storm-drill knobs (the multi-worker SSE storm): per-WORKER stream caps
#: sized so a 1000-client storm over 2 workers genuinely sheds, the same
#: tight write deadline + tiny stream buffers as the overload drill, and
#: a seal window deep enough that evicted clients resume with deltas
_STORM_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 64),
    "TPUDASH_MAX_STREAMS": ("max_streams", 400),
    "TPUDASH_MAX_CONCURRENCY": ("max_concurrency", 64),
    "TPUDASH_SSE_WRITE_DEADLINE": ("sse_write_deadline", 1.0),
    "TPUDASH_SHED_RETRY_AFTER": ("shed_retry_after", 1.0),
    "TPUDASH_SSE_SNDBUF": ("sse_sndbuf", 8192),
    "TPUDASH_BROADCAST_WINDOW": ("broadcast_window", 16),
}


def chaos_demo_source(cfg: Config):
    """The drill's MultiSource: three synthetic slices behind chaos."""
    from tpudash.sources.chaos import ChaosSource
    from tpudash.sources.fixture import SyntheticSource
    from tpudash.sources.multi import EndpointSpec, MultiSource

    # the registry already mapped TPUDASH_CHAOS → cfg.chaos (load_config);
    # the drill reuses it as the per-endpoint scenario override
    override = cfg.chaos
    children = []
    for label, default_spec in DEFAULT_DRILL.items():
        spec = default_spec
        if override and label != "chaos-a":
            spec = override
        inner = SyntheticSource(
            num_chips=min(cfg.synthetic_chips, 64),
            generation=cfg.generation,
        )
        src = ChaosSource(inner, spec) if spec else inner
        children.append(
            (EndpointSpec(url=f"synthetic://{label}", slice_name=label), src)
        )
    return MultiSource(cfg, children=children)


def make_chaos_app(cfg: Config | None = None):
    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService

    cfg = cfg or load_config()
    # short breaker cooldown + tight deadline so the drill's state
    # transitions are watchable within a coffee's attention span (env
    # overrides still win — load_config already applied them)
    if not env_is_set("TPUDASH_BREAKER_COOLDOWN"):
        cfg = dataclasses.replace(cfg, breaker_cooldown=10.0)
    if not env_is_set("TPUDASH_MULTI_DEADLINE"):
        cfg = dataclasses.replace(cfg, multi_deadline=1.0)
    service = DashboardService(cfg, chaos_demo_source(cfg))
    return DashboardServer(service).build_app(), cfg


# ---------------------------------------------------------------------------
# Overload drill — a client swarm against the admission/shedding layer.
# ---------------------------------------------------------------------------


def make_overload_server(cfg: Config | None = None):
    """(DashboardServer, cfg) under drill knobs: a chaos-latency synthetic
    source plus shedding limits a 100-client swarm will actually hit.
    Explicit env settings win over every drill default."""
    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService
    from tpudash.sources.chaos import ChaosSource
    from tpudash.sources.fixture import SyntheticSource

    cfg = cfg or load_config()
    for env_name, (field, value) in _OVERLOAD_KNOBS.items():
        if not env_is_set(env_name):
            cfg = dataclasses.replace(cfg, **{field: value})
    inner = SyntheticSource(
        num_chips=min(cfg.synthetic_chips, 128), generation=cfg.generation
    )
    source = ChaosSource(inner, cfg.chaos or OVERLOAD_SCENARIO)
    return DashboardServer(DashboardService(cfg, source)), cfg


class _ErrorTrap(logging.Handler):
    """Collects ERROR+ records — the drill's "zero unhandled exceptions
    in server logs" check reads these (aiohttp logs every handler
    traceback as ERROR on 'aiohttp.server')."""

    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.records: list = []

    def emit(self, record: logging.LogRecord) -> None:
        self.records.append(self.format(record))


async def _stalled_stream(host: str, port: int, sid: str, stop: asyncio.Event):
    """A deliberately-slow SSE consumer: tiny receive buffer, reads a few
    KB of the first event, then stops draining entirely — the shape of a
    wedged dashboard tab the write deadline must evict."""
    import socket as socketmod

    sock = socketmod.socket(socketmod.AF_INET, socketmod.SOCK_STREAM)
    sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_RCVBUF, 4096)
    sock.setblocking(False)
    loop = asyncio.get_running_loop()
    writer = None
    try:
        await loop.sock_connect(sock, (host, port))
        # limit=2048: asyncio's default StreamReader otherwise buffers
        # ~128KB in user space before pausing the transport — the "slow"
        # consumer would silently absorb many events instead of stalling
        reader, writer = await asyncio.open_connection(sock=sock, limit=2048)
        writer.write(
            (
                f"GET /api/stream HTTP/1.0\r\nHost: {host}\r\n"
                f"Cookie: tpudash_sid={sid}\r\n\r\n"
            ).encode()
        )
        await writer.drain()
        await asyncio.wait_for(reader.read(2048), timeout=10)  # first bytes
        await stop.wait()  # ...then never drain again
    except (OSError, asyncio.TimeoutError):
        pass  # the server evicting us closes the pipe — expected
    finally:
        with contextlib.suppress(OSError):
            if writer is not None:
                writer.close()
            else:
                sock.close()


async def run_overload_drill(
    clients: int = 100, seconds: float = 10.0, cfg: Config | None = None
) -> dict:
    """Drive the swarm; return a JSON-able summary with ``ok`` and the
    list of violated invariants (empty when the drill passes)."""
    from aiohttp import ClientSession, web

    # constructed in the executor: DashboardService.__init__ does real
    # file I/O (state checkpoint, history restore/sweep) and sources own
    # HTTP sessions — none of it belongs on the loop the drill is about
    # to measure (asynccheck rule ``async-blocking``)
    loop = asyncio.get_running_loop()
    server, cfg = await loop.run_in_executor(None, make_overload_server, cfg)
    app = server.build_app()

    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    host, port = runner.addresses[0][:2]
    base = f"http://{host}:{port}"

    stop = asyncio.Event()
    stats = {
        "ok_200": 0,
        "not_modified_304": 0,
        "shed_503": 0,
        "shed_with_retry_after": 0,
        "stale_frames": 0,
        "select_ok": 0,
        "stream_events": 0,
        "healthz_probes": 0,
        "healthz_failures": 0,
        "healthz_max_ms": 0.0,
    }

    from aiohttp import ClientError

    async def hammer(session: ClientSession, sid: str):
        cookies = {"tpudash_sid": sid}
        while not stop.is_set():
            try:
                async with session.get(
                    f"{base}/api/frame", cookies=cookies
                ) as r:
                    if r.status == 200:
                        body = await r.json()
                        if body.get("stale"):
                            stats["stale_frames"] += 1
                        else:
                            stats["ok_200"] += 1
                    elif r.status == 304:
                        stats["not_modified_304"] += 1
                    elif r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
                async with session.post(
                    f"{base}/api/select",
                    json={"toggle": "slice-0/1"},
                    cookies=cookies,
                ) as r:
                    if r.status == 200:
                        stats["select_ok"] += 1
                    elif r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
            except (OSError, ClientError):
                # a shed/reset/server-closed connection is the drill
                # working — the hammer client must keep hammering, not
                # die and silently thin the swarm (ClientError covers
                # aiohttp spellings like ServerDisconnectedError that
                # are NOT OSError subclasses)
                pass
            await asyncio.sleep(0)

    async def stream_reader(session: ClientSession, sid: str):
        try:
            async with session.get(
                f"{base}/api/stream", cookies={"tpudash_sid": sid}
            ) as r:
                if r.status == 503:
                    stats["shed_503"] += 1
                    if r.headers.get("Retry-After"):
                        stats["shed_with_retry_after"] += 1
                    return
                async for _line in r.content:
                    stats["stream_events"] += 1
                    if stop.is_set():
                        return
        except (OSError, ClientError, asyncio.TimeoutError):
            pass

    async def healthz_probe(session: ClientSession):
        # every probe is bounded and every failure is RECORDED: a hung
        # /healthz must fail the drill's <1s invariant, not block this
        # coroutine until teardown with healthz_max_ms frozen at its
        # last good value
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                async def probe():
                    async with session.get(f"{base}/healthz") as r:
                        await r.json()
                        return r.status

                status = await asyncio.wait_for(probe(), timeout=1.0)
                if status != 200:
                    stats["healthz_failures"] += 1
                ms = (time.monotonic() - t0) * 1e3
                stats["healthz_max_ms"] = max(stats["healthz_max_ms"], ms)
            except asyncio.TimeoutError:
                stats["healthz_failures"] += 1
                stats["healthz_max_ms"] = max(
                    stats["healthz_max_ms"], 1000.0
                )
            except (OSError, ClientError):
                stats["healthz_failures"] += 1
            stats["healthz_probes"] += 1
            await asyncio.sleep(0.25)

    # role split that stays sane at any --clients value: stalled and
    # stream roles never eat the whole budget, and at least one hammer
    # client always exists (without hammerers nothing sheds and the
    # drill would fail with a misleading "no sheds observed")
    clients = max(4, clients)
    n_stalled = min(max(2, clients // 20), clients // 4)
    n_streams = min(max(4, clients // 5), clients // 2)
    n_hammer = max(1, clients - n_stalled - n_streams)
    async with ClientSession() as session:
        # stalled consumers pre-select everything so their frames are big
        # enough to fill the (shrunken) buffers within a tick or two
        for i in range(n_stalled):
            try:
                await session.post(
                    f"{base}/api/select",
                    json={"all": True},
                    cookies={"tpudash_sid": f"stall-{i}"},
                )
            except OSError:
                pass
        # Phase A — attach the streams (including the stalled consumers)
        # and let them receive their first event BEFORE the hammer storm:
        # a slow consumer in the wild is a tab that attached while things
        # were calm and then wedged, and the warmup keeps the eviction
        # proof from racing 100 hammer clients for the frame lock.
        # Every spawn below is RETAINED in `tasks` (awaited, then
        # cancelled at teardown) — the asynccheck ``unretained-task``
        # rule holds this file to that.
        tasks = [
            asyncio.ensure_future(healthz_probe(session)),
            *(
                asyncio.ensure_future(
                    _stalled_stream(host, port, f"stall-{i}", stop)
                )
                for i in range(n_stalled)
            ),
            *(
                asyncio.ensure_future(
                    stream_reader(session, f"swarm-{i}")
                )
                for i in range(n_streams)
            ),
        ]
        await asyncio.sleep(min(3.0, max(1.0, seconds / 3.0)))
        # Phase B — the swarm
        tasks += [
            asyncio.ensure_future(hammer(session, f"swarm-{i}"))
            for i in range(n_hammer)
        ]
        await asyncio.sleep(seconds)
        stop.set()
        await asyncio.wait(tasks, timeout=10)
        for t in tasks:
            t.cancel()
        # /healthz and /api/timings still answer after the storm, and the
        # counters the runbook points at are actually there
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
        async with session.get(f"{base}/api/timings") as r:
            timings = await r.json()
    await runner.cleanup()
    logging.getLogger().removeHandler(trap)

    snap = server.overload.snapshot()
    failures = []
    if stats["shed_503"] == 0 or stats["shed_with_retry_after"] == 0:
        failures.append("no 503+Retry-After sheds observed")
    if stats["stale_frames"] == 0:
        failures.append("no stale:true degraded frames served")
    if snap["counters"]["evicted_slow_consumers"] == 0:
        failures.append("no slow consumers evicted by the write deadline")
    if stats["healthz_max_ms"] >= 1000.0 or stats["healthz_failures"] > 0:
        failures.append(
            f"healthz degraded: max {stats['healthz_max_ms']:.0f}ms, "
            f"{stats['healthz_failures']} failed/hung probe(s)"
        )
    if "overload" not in timings or "counters" not in timings["overload"]:
        failures.append("/api/timings lost the overload counters")
    # the loop-lag sanitizer must be live AND flat: overload protection
    # that holds while the event loop starves is no protection at all.
    # p50 (not max) is the assertion — a single GC pause or laggy CI tick
    # must not flake the drill, a *sustained* stall must fail it.
    lag = timings.get("loop_lag_ms") or {}
    if not lag.get("samples"):
        failures.append("loop-lag monitor recorded no heartbeat samples")
    elif lag.get("p50") is not None and lag["p50"] >= cfg.loop_lag_budget:
        failures.append(
            f"event-loop lag not flat: p50 {lag['p50']}ms >= "
            f"{cfg.loop_lag_budget:g}ms budget "
            f"({lag.get('slow_callbacks', 0)} slow callback(s))"
        )
    if health.get("ok") is not True:
        failures.append("healthz ok flapped under load")
    if trap.records:
        failures.append(
            f"{len(trap.records)} unhandled server exception(s): "
            + trap.records[0][:500]
        )
    return {
        "ok": not failures,
        "failures": failures,
        "clients": clients,
        "seconds": seconds,
        "requests": stats,
        "overload": snap,
        "loop_lag_ms": lag,
        "healthz_status": health.get("status"),
        "limits": snap["limits"],
    }


# ---------------------------------------------------------------------------
# Storm drill — a 1000-client SSE storm across the multi-process worker
# tier (tpudash.broadcast): the broadcast plane's overload contract.
# ---------------------------------------------------------------------------


def _raise_fd_limit(want: int = 65536) -> None:
    """A 1000-connection storm (plus worker processes inheriting this
    limit) needs more file descriptors than the usual soft 1024."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    target = min(hard, want) if hard > 0 else want
    if soft < target:
        resource.setrlimit(resource.RLIMIT_NOFILE, (target, hard))


# ---------------------------------------------------------------------------
# Resource-census assertions (leakcheck's runtime half): every drill
# captures {fds, threads} per process at a pre-storm steady state and
# asserts the post-storm steady state is back at (or under) it in every
# SURVIVING process — a tier that gains descriptors per storm is a slow
# outage at fleet scale.  Processes killed by the drill (new pid, or
# gone) have no pre baseline and are excluded by construction.
# ---------------------------------------------------------------------------


def _census_fingerprint(census) -> "dict | None":
    """{'fds','threads'} from a /healthz or worker-doc ``census`` entry
    (see tpudash.analysis.leakcheck.process_census); None if absent or
    the fd count was unreadable (-1)."""
    if not isinstance(census, dict):
        return None
    fds, threads = census.get("fds"), census.get("threads")
    if not isinstance(fds, int) or not isinstance(threads, int) or fds < 0:
        return None
    return {"fds": fds, "threads": threads}


def _census_growth(pre: dict, post: dict) -> dict:
    """Positive fd/thread growth between two fingerprints ({} = clean)."""
    return {
        k: post[k] - pre[k]
        for k in ("fds", "threads")
        if post.get(k, 0) > pre.get(k, 0)
    }


async def _assert_no_census_growth(
    pre: "dict[str, dict]",
    probe,
    failures: "list[str]",
    numbers: dict,
    deadline_s: float = 25.0,
) -> None:
    """Settle-poll ``probe()`` (async → {name: census doc}) until every
    process observed in BOTH steady states shows zero net fd/thread
    growth, or the deadline passes — then record the verdict.  The poll
    matters: evicted consumers, executor threads, and half-closed
    sockets drain over a few seconds after the load stops; the
    invariant is the *steady state*, not the instant the storm ends."""
    end = time.monotonic() + deadline_s
    post: "dict[str, dict]" = {}
    growth: "dict[str, dict]" = {}
    while True:
        latest = await probe()
        for name, census in (latest or {}).items():
            fp = _census_fingerprint(census)
            if fp is not None:
                post[name] = fp
        growth = {}
        for name, fp in pre.items():
            if name in post:
                g = _census_growth(fp, post[name])
                if g:
                    growth[name] = g
        if not growth or time.monotonic() >= end:
            break
        await asyncio.sleep(0.5)
    survivors = sorted(set(pre) & set(post))
    numbers["census"] = {
        "pre": pre,
        "post": post,
        "growth": growth,
        "survivors_checked": survivors,
    }
    if not survivors:
        failures.append(
            "census: no surviving process observed in both pre- and "
            "post-storm steady states"
        )
    for name, g in sorted(growth.items()):
        failures.append(
            f"census: {name} grew {g} between pre- and post-storm "
            "steady states (fd/thread leak)"
        )


#: the storm drill's ``/healthz`` prober, run as a SEPARATE PROCESS
#: (``python -c``): the drill process itself runs ~1000 client tasks, so
#: any in-process probe — coroutine or thread (GIL) — measures the
#: harness's own starvation, not the server's availability.  Fresh
#: connection per probe (SO_REUSEPORT hashes each to some worker), hard
#: socket timeout, one JSON summary on stdout at the end.
_HEALTHZ_PROBE_SRC = """
import http.client, json, sys, time
host, port = sys.argv[1], int(sys.argv[2])
settle, seconds = float(sys.argv[3]), float(sys.argv[4])
time.sleep(settle)
end = time.monotonic() + seconds
out = {"probes": 0, "failures": 0, "latencies_ms": []}
while time.monotonic() < end:
    t0 = time.monotonic()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=5.0)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            resp.read()
            out["latencies_ms"].append(round((time.monotonic() - t0) * 1e3, 2))
            if resp.status != 200:
                out["failures"] += 1
        finally:
            conn.close()
    except OSError:
        out["failures"] += 1
    out["probes"] += 1
    time.sleep(0.25)
print(json.dumps(out))
"""


def make_storm_server(cfg: "Config | None", workers: int):
    """(DashboardServer, cfg, bus_dir) for the storm: a plain synthetic
    source (the storm stresses FAN-OUT, not compose) under storm knobs,
    preflighted for worker mode.  Raises BroadcastSetupError where worker
    mode cannot run — the drill fails loudly, mirroring production's
    fail-fast contract."""
    import socket as socketmod
    import tempfile

    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService
    from tpudash.broadcast.supervisor import preflight
    from tpudash.sources.fixture import SyntheticSource

    cfg = cfg or load_config()
    for env_name, (field, value) in _STORM_KNOBS.items():
        if not env_is_set(env_name):
            cfg = dataclasses.replace(cfg, **{field: value})
    # an ephemeral public port for the SO_REUSEPORT worker sockets (bind
    # 0 to learn a free one; the tiny close-to-rebind race is acceptable
    # in a drill) and a private short-path bus dir
    with socketmod.socket(
        socketmod.AF_INET, socketmod.SOCK_STREAM
    ) as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    cfg = dataclasses.replace(
        cfg,
        workers=workers,
        host="127.0.0.1",
        port=port,
        broadcast_bus=cfg.broadcast_bus
        or tempfile.mkdtemp(prefix="tpudash-storm-"),
    )
    bus_dir = preflight(cfg)
    source = SyntheticSource(
        num_chips=min(cfg.synthetic_chips, 128), generation=cfg.generation
    )
    return DashboardServer(DashboardService(cfg, source)), cfg, bus_dir


def _storm_bin_idx(total: int, binary_share: float) -> set:
    """Global stream indices that negotiate the binary framing — spread
    evenly through the arrival ramp (arriving the binary cohort last
    would hand every one of them a shed 503 once the stream caps fill).
    Shared by the parent drill and every client-shard subprocess, so
    shards agree on roles without coordination."""
    n_bin = int(total * max(0.0, min(1.0, binary_share)))
    if not n_bin:
        return set()
    return {int(j * total / n_bin) for j in range(n_bin)}


async def run_storm_client_pool(
    host: str,
    port: int,
    start: int,
    count: int,
    total: int,
    ramp: float,
    seconds: float,
    binary_share: float,
) -> dict:
    """One shard of the storm's streaming population: global client
    indices ``[start, start+count)`` out of ``total``, each arriving at
    its ramp offset.  Run in SUBPROCESSES by the drill (``python -m
    tpudash.chaos storm-clients``): a single Python process cannot
    drive 2500 concurrent streams without measuring its own event-loop
    starvation instead of the tier — sharding puts the load generator
    on its own cores."""
    from aiohttp import ClientError, ClientSession, TCPConnector

    from tpudash.app import wire

    base = f"http://{host}:{port}"
    stop = asyncio.Event()
    pids: set = set()
    stats = {
        "stream_events": 0,
        "streams_served": 0,
        "shed_503": 0,
        "shed_with_retry_after": 0,
        "bin_streams_served": 0,
        "bin_template_events": 0,
        "bin_full_events": 0,
        "bin_delta_events": 0,
        "bin_framing_errors": 0,
    }

    async def stream_client(session: ClientSession, i: int, delay: float):
        """One JSON viewer: stream events until told to stop; a shed
        503 backs off Retry-After and retries — shed clients in the
        wild don't vanish, they come back."""
        cookies = {"tpudash_sid": f"storm-{i}"}
        await asyncio.sleep(delay)
        while not stop.is_set():
            try:
                async with session.get(
                    f"{base}/api/stream", cookies=cookies
                ) as r:
                    pid = r.headers.get("X-TPUDash-Worker")
                    if r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
                        await asyncio.sleep(
                            float(r.headers.get("Retry-After") or 1.0)
                        )
                        continue
                    if pid:
                        pids.add(pid)
                    stats["streams_served"] += 1
                    # chunk-level token counting instead of per-line
                    # Python iteration (a 4-byte carry makes the count
                    # boundary-safe; JSON bodies can't contain a bare
                    # "data:" — the key is always quoted)
                    carry = b""
                    async for chunk in r.content.iter_any():
                        data = carry + chunk
                        stats["stream_events"] += data.count(b"data:")
                        carry = data[-4:]
                        if stop.is_set():
                            return
            except (OSError, ClientError, asyncio.TimeoutError):
                await asyncio.sleep(0.2)

    async def bin_stream_client(session: ClientSession, i: int, delay: float):
        """One BINARY viewer (``?format=bin``): splits the TDB1 event
        framing incrementally and counts template/full/delta events —
        the mixed-population half of the storm.  Any framing violation
        is counted and fails the drill."""
        cookies = {"tpudash_sid": f"storm-{i}"}
        headers = {"Accept-Encoding": "identity"}
        await asyncio.sleep(delay)
        while not stop.is_set():
            try:
                async with session.get(
                    f"{base}/api/stream",
                    params={"format": "bin"},
                    cookies=cookies,
                    headers=headers,
                ) as r:
                    pid = r.headers.get("X-TPUDash-Worker")
                    if r.status == 503:
                        stats["shed_503"] += 1
                        if r.headers.get("Retry-After"):
                            stats["shed_with_retry_after"] += 1
                        await asyncio.sleep(
                            float(r.headers.get("Retry-After") or 1.0)
                        )
                        continue
                    if pid:
                        pids.add(pid)
                    stats["bin_streams_served"] += 1
                    buf = b""
                    async for chunk in r.content.iter_any():
                        buf += chunk
                        try:
                            evts, buf = wire.split_bin_events(buf)
                        except wire.WireError:
                            stats["bin_framing_errors"] += 1
                            return
                        for etype, _eid, _body in evts:
                            if etype == wire.EVT_TEMPLATE:
                                stats["bin_template_events"] += 1
                            elif etype == wire.EVT_FULL:
                                stats["bin_full_events"] += 1
                            elif etype == wire.EVT_DELTA:
                                stats["bin_delta_events"] += 1
                            stats["stream_events"] += 1
                        if stop.is_set():
                            return
            except (OSError, ClientError, asyncio.TimeoutError):
                await asyncio.sleep(0.2)

    bin_idx = _storm_bin_idx(total, binary_share)
    async with ClientSession(connector=TCPConnector(limit=0)) as session:
        tasks = [
            asyncio.ensure_future(
                (bin_stream_client if i in bin_idx else stream_client)(
                    session, i, ramp * i / max(1, total)
                )
            )
            for i in range(start, start + count)
        ]
        await asyncio.sleep(seconds)
        stop.set()
        await asyncio.wait(tasks, timeout=10)
        for t in tasks:
            t.cancel()
    stats["pids"] = sorted(pids)
    return stats


async def run_storm_drill(
    clients: int = 1000,
    workers: int = 2,
    seconds: float = 30.0,
    cfg: "Config | None" = None,
    binary_share: float = 0.25,
) -> dict:
    """The broadcast plane's soak: a ``clients``-strong SSE storm against
    ``workers`` real fan-out worker processes (SO_REUSEPORT + frame bus),
    asserting the overload contract holds in EVERY process:

    - the storm spreads across >= 2 distinct worker pids;
    - per-worker stream caps shed the overflow with 503 + Retry-After;
    - deliberately-stalled consumers are evicted by the write deadline;
    - ``loop_lag_ms`` p50 stays under budget in the compose process and
      every observed worker;
    - zero unhandled exceptions in any process's logs;
    - ``/healthz`` keeps answering throughout — probed from a SEPARATE
      process (in-process probes, coroutine or thread, measure the
      drill's own 1000-task starvation, not the server), asserting zero
      failed probes and p50 under a second.

    ISSUE 11 additions: ``binary_share`` of the streaming population
    negotiates ``?format=bin`` (TDB1 framing: template → columnar full →
    binary deltas, counted per event type with framing validated), and
    the frame-bus transport is asserted on — in shm mode every seal
    must fan out as ring DESCRIPTORS (per-worker bus bytes O(1) in blob
    bytes) with the figure template shipped once per worker per epoch,
    never per seal.
    """
    from aiohttp import (
        ClientError,
        ClientSession,
        ClientTimeout,
        TCPConnector,
    )

    from tpudash.broadcast.supervisor import BroadcastSetupError, Supervisor

    _raise_fd_limit()
    loop = asyncio.get_running_loop()
    try:
        server, cfg, bus_dir = await loop.run_in_executor(
            None, make_storm_server, cfg, workers
        )
    except BroadcastSetupError as e:
        return {"ok": False, "failures": [f"preflight: {e}"]}
    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    sup = Supervisor(cfg, server, bus_dir, log_dir=bus_dir)
    await sup.start()
    base = f"http://{cfg.host}:{cfg.port}"

    stats = {
        "stream_events": 0,
        "streams_served": 0,
        "shed_503": 0,
        "shed_with_retry_after": 0,
        "healthz_probes": 0,
        "healthz_failures": 0,
        "healthz_max_ms": 0.0,
        "bin_streams_served": 0,
        "bin_template_events": 0,
        "bin_full_events": 0,
        "bin_delta_events": 0,
        "bin_framing_errors": 0,
    }
    hz_lat: "list[float]" = []
    stream_pids: set = set()
    stop = asyncio.Event()

    async def wait_for_workers() -> bool:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if len(sup.publisher.workers()) >= workers:
                return True
            await asyncio.sleep(0.25)
        return False

    async def tier_censuses() -> dict:
        """{name: census} for the compose process (in-process) and every
        worker pid reachable through the shared port (fresh connection
        per probe so SO_REUSEPORT hashes across pids)."""
        out: dict = {"compose": process_census()}
        async with ClientSession(
            connector=TCPConnector(force_close=True),
            timeout=ClientTimeout(total=2.0),
        ) as s:
            for _ in range(20 * workers):
                if len(out) >= workers + 1:
                    break
                try:
                    async with s.get(f"{base}/healthz") as r:
                        doc = await r.json(content_type=None)
                except (OSError, ClientError, asyncio.TimeoutError, ValueError):
                    continue
                wdoc = (doc or {}).get("worker") or {}
                if wdoc.get("pid") is not None:
                    out[f"worker-{wdoc['pid']}"] = wdoc.get("census")
        return out

    failures = []
    census_numbers: dict = {}
    worker_docs: dict = {}
    shard_procs: list = []
    try:
        if not await wait_for_workers():
            failures.append(
                f"only {len(sup.publisher.workers())}/{workers} workers "
                "connected to the bus within 60s"
            )
        else:
            # pre-storm steady state: the census every surviving process
            # must be back at once the storm drains (leakcheck runtime)
            await warm_default_executor()
            pre_census = {
                name: fp
                for name, c in (await tier_censuses()).items()
                for fp in (_census_fingerprint(c),)
                if fp is not None
            }
            clients = max(8, clients)
            n_stalled = min(max(4, clients // 50), 32)
            n_streams = clients - n_stalled
            # arrivals staggered over the first part of the run: a
            # thousand simultaneous connects measures the load
            # generator's own accept loop, not the worker tier.  The
            # ramp scales with the population (≥ clients/250 s) so the
            # 2500-client shape arrives as a staged wave, capped at 40%
            # of the run
            ramp = min(
                max(1.0, seconds / 3.0, clients / 250.0), seconds * 0.4
            )
            # probe only AFTER the connect surge settles: the invariant
            # is steady-state availability.  Measured on a 2-core box,
            # 1000 clients arriving over the ramp keep the workers'
            # accept/handshake path saturated for a few seconds past the
            # last arrival; probes inside that window time the surge
            # being drained, not the serving plane the drill asserts on.
            settle = ramp + max(3.0, seconds / 3.0)
            hz_proc = await asyncio.create_subprocess_exec(
                sys.executable,
                "-c",
                _HEALTHZ_PROBE_SRC,
                cfg.host,
                str(cfg.port),
                str(settle),
                str(max(1.0, seconds - settle)),
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
            )
            # the streaming population runs in SHARD SUBPROCESSES
            # (``storm-clients``): one Python process cannot drive 2500
            # concurrent streams without measuring its own event-loop
            # starvation instead of the tier.  Only the stalled
            # consumers (few, near-zero CPU) stay in this process.
            n_shards = max(1, min(os.cpu_count() or 2, n_streams // 400))
            per = (n_streams + n_shards - 1) // n_shards
            start_i = 0
            while start_i < n_streams:
                count = min(per, n_streams - start_i)
                shard_procs.append(
                    await asyncio.create_subprocess_exec(
                        sys.executable,
                        "-m",
                        "tpudash.chaos",
                        "storm-clients",
                        "--host", cfg.host,
                        "--port", str(cfg.port),
                        "--start", str(start_i),
                        "--count", str(count),
                        "--total", str(n_streams),
                        "--ramp", str(ramp),
                        "--seconds", str(seconds),
                        "--binary-share", str(binary_share),
                        stdout=asyncio.subprocess.PIPE,
                        stderr=asyncio.subprocess.DEVNULL,
                    )
                )
                start_i += count
            tasks = [
                asyncio.ensure_future(
                    _stalled_stream(
                        cfg.host, cfg.port, f"storm-stall-{i}", stop
                    )
                )
                for i in range(n_stalled)
            ]
            shard_docs = []
            for proc in shard_procs:
                try:
                    out, _ = await asyncio.wait_for(
                        proc.communicate(), timeout=seconds + 45
                    )
                    shard_docs.append(json.loads(out or b"{}"))
                except (asyncio.TimeoutError, ValueError):
                    with contextlib.suppress(ProcessLookupError):
                        proc.kill()
                    failures.append("a storm client shard hung or died")
            stop.set()
            await asyncio.wait(tasks, timeout=15)
            for t in tasks:
                t.cancel()
            for doc in shard_docs:
                for key in (
                    "stream_events", "streams_served", "shed_503",
                    "shed_with_retry_after", "bin_streams_served",
                    "bin_template_events", "bin_full_events",
                    "bin_delta_events", "bin_framing_errors",
                ):
                    stats[key] += doc.get(key, 0)
                stream_pids.update(doc.get("pids") or [])
            try:
                hz_out, _ = await asyncio.wait_for(
                    hz_proc.communicate(), timeout=15
                )
                hz_doc = json.loads(hz_out or b"{}")
            except (asyncio.TimeoutError, ValueError):
                try:
                    hz_proc.kill()
                except ProcessLookupError:
                    pass
                hz_doc = {}
            stats["healthz_probes"] = hz_doc.get("probes", 0)
            stats["healthz_failures"] = hz_doc.get("failures", 0)
            hz_lat.extend(hz_doc.get("latencies_ms") or [])
            stats["healthz_max_ms"] = max(hz_lat, default=0.0)
            # collect every worker's vitals: force a fresh connection
            # per probe so SO_REUSEPORT hashes us across pids
            async with ClientSession(
                connector=TCPConnector(force_close=True),
                timeout=ClientTimeout(total=2.0),
            ) as probeses:
                for _ in range(80):
                    if len(worker_docs) >= workers:
                        break
                    try:
                        async with probeses.get(f"{base}/healthz") as r:
                            doc = await r.json()
                    except (OSError, ClientError, asyncio.TimeoutError):
                        continue
                    wdoc = doc.get("worker") or {}
                    if wdoc.get("pid") is not None:
                        worker_docs[str(wdoc["pid"])] = wdoc
            # post-storm steady state: zero net fd/thread growth in the
            # compose process and every surviving worker (settle-polled)
            await _assert_no_census_growth(
                pre_census, tier_censuses, failures, census_numbers
            )
    finally:
        bus_stats = sup.publisher.stats() if sup.publisher else {}
        await sup.stop()
        logging.getLogger().removeHandler(trap)

    # -- invariants ----------------------------------------------------------
    budget = cfg.loop_lag_budget
    lat = sorted(hz_lat)
    hz_p50 = lat[len(lat) // 2] if lat else None
    stats["healthz_p50_ms"] = hz_p50
    if not failures:
        if len(stream_pids) < min(2, workers):
            failures.append(
                f"storm never spread across workers: pids {sorted(stream_pids)}"
            )
        if stats["shed_503"] == 0 or stats["shed_with_retry_after"] == 0:
            failures.append(
                "no 503+Retry-After sheds observed (per-worker stream cap)"
            )
        evicted = sum(
            (d.get("counters") or {}).get("evicted_slow_consumers", 0)
            for d in worker_docs.values()
        )
        if evicted == 0:
            failures.append(
                "no slow consumers evicted by any worker's write deadline"
            )
        if stats["stream_events"] < clients:
            failures.append(
                f"storm barely streamed: {stats['stream_events']} events "
                f"for {clients} clients"
            )
        if stats["healthz_failures"] > 0 or not lat:
            failures.append(
                f"healthz availability: {stats['healthz_failures']} "
                f"failed probe(s) of {stats['healthz_probes']}"
            )
        elif hz_p50 >= 1000.0:
            failures.append(
                f"healthz degraded: p50 {hz_p50:.0f}ms >= 1000ms "
                f"(max {stats['healthz_max_ms']:.0f}ms)"
            )
        if len(worker_docs) < workers:
            failures.append(
                f"vitals collected from only {len(worker_docs)}/{workers} "
                "workers"
            )
        # loop-lag flatness in EVERY process: the compose process's own
        # monitor plus each worker's, as reported on its /healthz
        compose_lag = server.loop_monitor.summary()
        lags = {"compose": compose_lag}
        for pid, d in worker_docs.items():
            lags[f"worker-{pid}"] = d.get("loop_lag_ms") or {}
        for name, lag in lags.items():
            if not lag.get("samples"):
                failures.append(f"{name}: loop-lag monitor has no samples")
            elif lag.get("p50") is not None and lag["p50"] >= budget:
                failures.append(
                    f"{name}: loop lag p50 {lag['p50']}ms >= {budget:g}ms"
                )
        # zero unhandled exceptions — compose trap + every worker log
        if trap.records:
            failures.append(
                f"{len(trap.records)} unhandled compose-process "
                "exception(s): " + trap.records[0][:500]
            )
        worker_log_errors = await loop.run_in_executor(
            None, _scan_worker_logs, bus_dir
        )
        if worker_log_errors:
            failures.append(
                f"worker logs show unhandled exceptions: "
                f"{worker_log_errors[0][:500]}"
            )
        # the mixed binary population actually streamed the TDB1 plane:
        # template before fulls, columnar fulls, steady-state deltas,
        # and not one framing violation across the whole storm
        if binary_share > 0:
            if stats["bin_streams_served"] == 0:
                failures.append("no binary (?format=bin) streams served")
            if stats["bin_template_events"] == 0:
                failures.append("binary streams never received a template")
            if stats["bin_full_events"] == 0:
                failures.append(
                    "binary streams never received a columnar full"
                )
            if stats["bin_delta_events"] == 0:
                failures.append("binary streams never received a delta")
            if stats["bin_framing_errors"]:
                failures.append(
                    f"{stats['bin_framing_errors']} TDB1 framing "
                    "violation(s) on binary streams"
                )
        # seal-ring transport: in shm mode every seal fans out as ring
        # descriptors — per-worker bus bytes O(1) in blob bytes — and
        # the figure template ships once per worker per epoch, never
        # per seal (that is what keeps bus publish CPU flat in worker
        # count; the 1/2/4-worker guard itself lives in
        # bench.bench_bus_fanout)
        bc = bus_stats.get("counters") or {}
        ring_info = bus_stats.get("ring") or {}
        if ring_info.get("mode") == "shm":
            seals_pub = bc.get("seals_published", 0)
            if seals_pub and not bc.get("desc_bytes_published"):
                failures.append(
                    "shm ring active but no descriptor messages published"
                )
            if seals_pub > workers and bc.get("templates_published", 0) >= (
                seals_pub * max(1, workers)
            ):
                failures.append(
                    "figure templates re-shipped per seal instead of per "
                    "(worker, epoch)"
                )
            per_msg = bc.get("desc_bytes_published", 0) / max(
                1, seals_pub * max(1, workers)
            )
            if per_msg > 8192:
                failures.append(
                    f"ring-mode seal messages average {per_msg:.0f}B — "
                    "descriptor fan-out is carrying blob-scale bytes"
                )
    return {
        "ok": not failures,
        "failures": failures,
        "clients": clients,
        "workers": workers,
        "seconds": seconds,
        "requests": stats,
        "stream_worker_pids": sorted(stream_pids),
        "worker_vitals": worker_docs,
        "compose_loop_lag_ms": server.loop_monitor.summary(),
        "supervisor_restarts": sup.restarts,
        "bus": bus_stats,
        "census": census_numbers.get("census"),
    }


# ---------------------------------------------------------------------------
# Killall drill — crash-anything: SIGKILL the compose process mid-storm,
# SIGKILL a worker, SIGKILL a snapshotting store process mid-snapshot, and
# verify follower catch-up through leader-side segment reclaim.
# ---------------------------------------------------------------------------

#: killall-drill knobs: a live tier small enough to boot fast, with a
#: persistent tsdb sealing constantly (the compose SIGKILL lands mid
#: seal-thread activity by construction) and a seal window deep enough
#: that mid-outage reconnects resume with deltas
_KILLALL_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 32),
    "TPUDASH_MAX_STREAMS": ("max_streams", 200),
    "TPUDASH_MAX_CONCURRENCY": ("max_concurrency", 64),
    "TPUDASH_SSE_WRITE_DEADLINE": ("sse_write_deadline", 2.0),
    "TPUDASH_BROADCAST_WINDOW": ("broadcast_window", 16),
    "TPUDASH_TSDB_CHUNK_POINTS": ("tsdb_chunk_points", 8),
    "TPUDASH_TSDB_FLUSH_INTERVAL": ("tsdb_flush_interval", 1.0),
}

#: how long the drill stretches the compose child's first restart —
#: long enough to assert the degraded window, short enough for CI
_KILLALL_COMPOSE_BACKOFF = 4.0

#: the snapshot-phase child: appends near-now frames and snapshots
#: continuously so the parent's SIGKILL lands mid-append/mid-snapshot
#: with high probability (the "seal thread" kill of the sequence)
_SNAPSHOT_CHILD = """
import sys, time, numpy as np
from tpudash.tsdb import TSDB, FLEET_SERIES
from tpudash.tsdb.snapshot import SnapshotError, take_snapshot
store = TSDB(path=sys.argv[1], chunk_points=4)
snap_root = sys.argv[2]
keys = [f"slice-0/{i}" for i in range(8)] + [FLEET_SERIES]
cols = ["tensorcore_utilization", "hbm_usage_ratio"]
i = 0
while True:
    mat = np.full((len(keys), len(cols)), float(i % 97), dtype=np.float32)
    store.append_frame(time.time() - 60.0 + i * 0.05, keys, cols, mat)
    store.flush()
    if i and i % 20 == 0:
        try:
            take_snapshot(store, snap_root)
        except SnapshotError as e:
            print(f"snapshot failed: {e}", file=sys.stderr)
    i += 1
"""

#: the follower-phase leader: tiny segments + tiny retention so files
#: rotate and get reclaimed WHILE the follower tails them
_LEADER_CHILD = """
import sys, time, numpy as np
import tpudash.tsdb.store as storemod
storemod._SEG_MAX_BYTES = 6000  # rotate constantly: reclaim needs closed files
from tpudash.tsdb import TSDB, FLEET_SERIES
store = TSDB(path=sys.argv[1], chunk_points=4,
             retention_raw_s=6.0, retention_1m_s=6.0, retention_10m_s=6.0)
keys = [f"slice-0/{i}" for i in range(8)] + [FLEET_SERIES]
cols = ["tensorcore_utilization", "hbm_usage_ratio"]
i = 0
while True:
    mat = np.full((len(keys), len(cols)), float(i % 97), dtype=np.float32)
    store.append_frame(time.time(), keys, cols, mat)
    store.flush()
    i += 1
    time.sleep(0.02)
"""


def make_killall_tier(cfg: "Config | None", workers: int):
    """(cfg, bus_dir, work_dir) for the killall drill: a supervised tier
    over a synthetic source with a PERSISTENT tsdb and state checkpoint
    (the compose child must have something to reload), preflighted for
    worker mode — fails loudly where worker mode cannot run."""
    import socket as socketmod
    import tempfile

    from tpudash.broadcast.supervisor import preflight

    cfg = cfg or load_config()
    for env_name, (field, value) in _KILLALL_KNOBS.items():
        if not env_is_set(env_name):
            cfg = dataclasses.replace(cfg, **{field: value})
    work_dir = tempfile.mkdtemp(prefix="tpudash-killall-")
    with socketmod.socket(
        socketmod.AF_INET, socketmod.SOCK_STREAM
    ) as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    cfg = dataclasses.replace(
        cfg,
        source="synthetic",
        workers=workers,
        host="127.0.0.1",
        port=port,
        broadcast_bus=os.path.join(work_dir, "bus"),
        tsdb_path=os.path.join(work_dir, "store"),
        state_path=os.path.join(work_dir, "state.json"),
    )
    bus_dir = preflight(cfg)
    return cfg, bus_dir, work_dir


async def _killall_read_event(resp, deadline: float = 30.0):
    """(event_id, payload dict) of the next real SSE event on an
    identity-encoded stream."""

    async def go():
        buf = b""
        async for chunk in resp.content.iter_any():
            buf += chunk
            while b"\n\n" in buf:
                evt, buf = buf.split(b"\n\n", 1)
                if evt.startswith(b":"):
                    continue  # keepalive
                eid, payload = None, None
                for line in evt.split(b"\n"):
                    if line.startswith(b"id: "):
                        eid = line[4:].decode()
                    elif line.startswith(b"data: "):
                        payload = json.loads(line[6:])
                if payload is not None:
                    return eid, payload
        raise AssertionError("stream ended without an event")

    return await asyncio.wait_for(go(), deadline)


async def _killall_stream_once(session, base, sid, last_id=None):
    """Open /api/stream once, read one event, close.  Returns
    (event_id, payload) or (None, None) after exhausting retries."""
    from aiohttp import ClientError

    headers = {"Accept-Encoding": "identity"}
    if last_id is not None:
        headers["Last-Event-ID"] = last_id
    for _ in range(40):
        try:
            resp = await session.get(
                f"{base}/api/stream",
                headers=headers,
                cookies={"tpudash_sid": sid},
            )
        except (OSError, ClientError):
            await asyncio.sleep(0.25)
            continue
        if resp.status != 200:
            resp.close()
            await asyncio.sleep(0.25)
            continue
        try:
            eid, payload = await _killall_read_event(resp)
        except (OSError, ClientError, asyncio.TimeoutError):
            resp.close()
            await asyncio.sleep(0.25)
            continue
        resp.close()
        return eid, payload
    return None, None


def _snapshot_kill_phase(work_dir: str) -> dict:
    """SIGKILL a store process mid-append/mid-snapshot, twice, then
    prove every snapshot directory either restores COMPLETELY or is
    refused — never a silently partial store — and time one clean
    snapshot for the job summary."""
    import random
    import subprocess

    from tpudash.tsdb import TSDB
    from tpudash.tsdb.snapshot import (
        SnapshotError,
        restore_snapshot,
        take_snapshot,
    )

    store_dir = os.path.join(work_dir, "snapstore")
    snap_root = os.path.join(work_dir, "snaps")
    os.makedirs(snap_root, exist_ok=True)
    rng = random.Random(11)
    stderr_tail = b""
    for _ in range(2):
        proc = subprocess.Popen(
            [sys.executable, "-c", _SNAPSHOT_CHILD, store_dir, snap_root],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        time.sleep(2.0 + rng.random())
        proc.send_signal(signal.SIGKILL)
        _, err = proc.communicate()
        stderr_tail += err or b""
    results = {"complete": 0, "refused": 0, "silently_partial": 0}
    entries = sorted(os.listdir(snap_root))
    for i, name in enumerate(entries):
        snap = os.path.join(snap_root, name)
        dest = os.path.join(work_dir, f"restore-{i}")
        try:
            restore_snapshot(snap, dest)
        except SnapshotError:
            results["refused"] += 1
            continue
        # a restore that "succeeded" must load cleanly AND completely:
        # the CRC walk truncates torn tails, so any size change after
        # load means the restore let partial data through
        sizes = {
            n: os.path.getsize(os.path.join(dest, n))
            for n in os.listdir(dest)
            if n.endswith(".seg")
        }
        restored = TSDB(path=dest, read_only=True)
        after = {
            n: os.path.getsize(os.path.join(dest, n)) for n in sizes
        }
        if restored.stats()["raw_points"] > 0 and sizes == after:
            results["complete"] += 1
        else:
            results["silently_partial"] += 1
    # one clean snapshot, timed, of whatever survived the kills
    store = TSDB(path=store_dir, chunk_points=4)
    snap = take_snapshot(store, snap_root)
    failures = []
    if results["complete"] == 0:
        failures.append("no snapshot survived the SIGKILLs complete")
    if results["silently_partial"]:
        failures.append(
            f"{results['silently_partial']} snapshot(s) restored PARTIAL "
            "data without refusing"
        )
    if b"Traceback" in stderr_tail:
        failures.append(
            "snapshot child crashed with a traceback before the kill: "
            + stderr_tail.decode(errors="replace")[:300]
        )
    return {
        "failures": failures,
        "snapshots_seen": len(entries),
        **results,
        "snapshot_duration_ms": snap["duration_ms"],
        "snapshot_bytes": snap["bytes"],
        "snapshot_files": snap["files"],
    }


def _follower_phase(work_dir: str) -> dict:
    """A follower tails a live leader whose tiny retention reclaims
    segments mid-tail; after the leader is SIGKILLed the follower must
    have converged with everything the leader's store still holds —
    replication lag measured and bounded throughout."""
    import subprocess

    from tpudash.tsdb import FLEET_SERIES, TSDB
    from tpudash.tsdb.follower import FollowerTSDB

    leader_dir = os.path.join(work_dir, "leader")
    os.makedirs(leader_dir, exist_ok=True)
    failures = []
    proc = subprocess.Popen(
        [sys.executable, "-c", _LEADER_CHILD, leader_dir],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    applied_t0 = time.monotonic()
    try:
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and not os.listdir(leader_dir):
            time.sleep(0.1)
        follower = FollowerTSDB(leader_dir, poll_interval_s=0.25)
        follower.start()
        # run long enough for the leader's 6 s retention to reclaim
        # whole segment files out from under the tail
        deadline = time.monotonic() + 14.0
        max_lag = 0.0
        while time.monotonic() < deadline:
            rep = follower.replication
            if rep["lag_s"] is not None:
                max_lag = max(max_lag, rep["lag_s"])
            if rep["files_reclaimed"] > 0 and time.monotonic() > applied_t0 + 9:
                break
            time.sleep(0.25)
    finally:
        proc.send_signal(signal.SIGKILL)
        _, err = proc.communicate()
    # final catch-up after the leader died mid-write
    follower.poll()
    time.sleep(0.3)
    follower.poll()
    follower.close()
    rep = dict(follower.replication)
    # the leader is dead: loading its directory is now safe (the torn
    # tail its kill left gets truncated, exactly like a restart would)
    leader = TSDB(path=leader_dir, chunk_points=4)
    lp, fp = leader.stats()["raw_points"], follower.stats()["raw_points"]
    if rep["files_reclaimed"] == 0:
        failures.append(
            "leader never reclaimed a segment under the follower "
            "(drill too short or retention broke)"
        )
    if rep["stuck_files"]:
        failures.append(f"follower poisoned files: {rep['stuck_files']}")
    if fp < lp:
        failures.append(
            f"follower lost data: {fp} points vs leader's surviving {lp}"
        )
    if rep["lag_s"] is None or max_lag > 5.0:
        failures.append(
            f"replication lag unmeasured or unbounded (max {max_lag:.2f}s)"
        )
    # range-query convergence over the leader's surviving window: every
    # point the leader still serves, the follower serves identically
    lo, hi = leader.earliest_ms(0), leader.latest_ms()
    converged = None
    if lo is not None and hi is not None:
        l_pts = leader.raw_window(FLEET_SERIES, "hbm_usage_ratio", lo, hi)
        f_pts = follower.raw_window(FLEET_SERIES, "hbm_usage_ratio", lo, hi)
        f_map = dict(f_pts)
        missing = [t for t, v in l_pts if f_map.get(t) != v]
        converged = not missing
        if missing:
            failures.append(
                f"follower range diverges from leader on {len(missing)} "
                f"of {len(l_pts)} surviving points"
            )
    elapsed = time.monotonic() - applied_t0
    if b"Traceback" in (err or b""):
        failures.append(
            "leader child crashed before the kill: "
            + (err or b"").decode(errors="replace")[:300]
        )
    return {
        "failures": failures,
        "replication_lag_s": rep.get("lag_s"),
        "replication_max_lag_s": round(max_lag, 3),
        "files_reclaimed_under_tail": rep["files_reclaimed"],
        "records_applied": rep["records_applied"],
        "follower_points": fp,
        "leader_surviving_points": lp,
        "converged": converged,
        "follower_catchup_points_per_s": (
            int(rep["records_applied"] / elapsed) if elapsed > 0 else None
        ),
    }


async def run_killall_drill(
    clients: int = 24, workers: int = 2, cfg: "Config | None" = None
) -> dict:
    """Crash-anything, asserted end to end: SIGKILL the compose process
    mid-storm (workers serve stale ``/api/frame`` with ``stale: true``
    and a ``compose_down`` alert, ``/healthz`` tells the truth, NO
    worker exits, and a mid-outage ``Last-Event-ID`` reconnect resumes
    with a DELTA from the retained mirrors); the restarted compose
    reloads the tsdb + state, re-snapshots every worker over the bus,
    and fresh frames resume with seal seqs that can never alias the old
    epoch's.  Then SIGKILL a worker (supervisor restarts it, serving
    never stops), SIGKILL a snapshotting store mid-snapshot (restore
    loads complete sets and REFUSES torn ones), and verify follower
    catch-up through leader-side segment reclaim with bounded,
    measured replication lag."""
    from aiohttp import (
        ClientError,
        ClientSession,
        ClientTimeout,
        TCPConnector,
    )

    from tpudash.broadcast.supervisor import (
        BroadcastSetupError,
        TierSupervisor,
    )

    _raise_fd_limit()
    loop = asyncio.get_running_loop()
    try:
        cfg, bus_dir, work_dir = await loop.run_in_executor(
            None, make_killall_tier, cfg, workers
        )
    except BroadcastSetupError as e:
        return {"ok": False, "failures": [f"preflight: {e}"]}
    sup = TierSupervisor(
        cfg,
        bus_dir,
        log_dir=bus_dir,
        compose_backoff=_KILLALL_COMPOSE_BACKOFF,
    )
    await sup.start()
    base = f"http://{cfg.host}:{cfg.port}"
    failures: "list[str]" = []
    numbers: dict = {"clients": clients, "workers": workers}
    stop = asyncio.Event()
    stream_events = {"n": 0}

    async def storm_client(session, i):
        """Background viewer: stream events, reconnect on any drop with
        the last event id — the population that must survive every kill."""
        last_id = None
        cookies = {"tpudash_sid": f"killall-{i}"}
        headers = {"Accept-Encoding": "identity"}
        while not stop.is_set():
            try:
                hdrs = dict(headers)
                if last_id:
                    hdrs["Last-Event-ID"] = last_id
                async with session.get(
                    f"{base}/api/stream", headers=hdrs, cookies=cookies
                ) as r:
                    if r.status != 200:
                        await asyncio.sleep(0.5)
                        continue
                    buf = b""
                    async for chunk in r.content.iter_any():
                        if stop.is_set():
                            return
                        buf += chunk
                        while b"\n\n" in buf:
                            evt, buf = buf.split(b"\n\n", 1)
                            for line in evt.split(b"\n"):
                                if line.startswith(b"id: "):
                                    last_id = line[4:].decode()
                                    stream_events["n"] += 1
            except (OSError, ClientError, asyncio.TimeoutError):
                await asyncio.sleep(0.3)

    async def fetch_frame(session, sid="killall-probe"):
        try:
            async with session.get(
                f"{base}/api/frame",
                cookies={"tpudash_sid": sid},
                headers={"Accept-Encoding": "identity"},
            ) as r:
                if r.status != 200:
                    return r.status, None
                return 200, await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError):
            return None, None

    async def fetch_json(session, path):
        try:
            async with session.get(
                f"{base}{path}", headers={"Accept-Encoding": "identity"}
            ) as r:
                return await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError, ValueError):
            return None

    async def tier_censuses() -> dict:
        """{name: census} per worker pid reachable through the shared
        port (fresh connection per probe → SO_REUSEPORT scatters).  The
        compose process is killed by design mid-drill, so only workers
        — matched by pid — carry a pre/post baseline here."""
        out: dict = {}
        async with ClientSession(
            connector=TCPConnector(force_close=True),
            timeout=ClientTimeout(total=2.0),
        ) as s:
            for _ in range(20 * workers):
                if len(out) >= workers:
                    break
                try:
                    async with s.get(f"{base}/healthz") as r:
                        doc = await r.json(content_type=None)
                except (OSError, ClientError, asyncio.TimeoutError, ValueError):
                    continue
                wdoc = (doc or {}).get("worker") or {}
                if wdoc.get("pid") is not None:
                    out[f"worker-{wdoc['pid']}"] = wdoc.get("census")
        return out

    pre_census: "dict[str, dict]" = {}
    tasks: "list[asyncio.Task]" = []
    try:
        async with ClientSession(connector=TCPConnector(limit=0)) as session:
            # -- phase 0: tier ready -----------------------------------------
            deadline = time.monotonic() + 90.0
            ready = False
            while time.monotonic() < deadline:
                status, frame = await fetch_frame(session)
                wdoc = await fetch_json(session, "/api/workers")
                bus_workers = (
                    len(((wdoc or {}).get("bus") or {}).get("workers") or [])
                )
                if status == 200 and frame is not None and bus_workers >= workers:
                    ready = True
                    break
                await asyncio.sleep(0.5)
            if not ready:
                failures.append("tier never became ready (90s)")
                raise _DrillAbort()
            # pre-storm steady state: the census every worker that
            # survives the kill sequence must be back at afterwards
            pre_census.update(
                {
                    name: fp
                    for name, c in (await tier_censuses()).items()
                    for fp in (_census_fingerprint(c),)
                    if fp is not None
                }
            )

            # -- phase 1: storm + resume probe --------------------------------
            tasks = [
                asyncio.ensure_future(storm_client(session, i))
                for i in range(max(4, clients))
            ]
            probe_sid = "killall-resume"
            #: the live (event_id, kind) tape of one dedicated viewer —
            #: the mid-outage resume picks an ack from it whose
            #: successors are all deltas (an occasional seal is
            #: structural — axis maxima drift — and a full-only seal in
            #: the gap legitimately forces a full frame; the invariant
            #: under test is that the RETAINED WINDOW serves the delta
            #: chain through the outage, so the probe must ack a
            #: delta-resumable position)
            probe_events: "list[tuple[str, str]]" = []

            async def resume_probe():
                try:
                    async with session.get(
                        f"{base}/api/stream",
                        headers={"Accept-Encoding": "identity"},
                        cookies={"tpudash_sid": probe_sid},
                    ) as r:
                        buf = b""
                        async for chunk in r.content.iter_any():
                            buf += chunk
                            while b"\n\n" in buf:
                                evt, buf = buf.split(b"\n\n", 1)
                                eid = kind = None
                                for line in evt.split(b"\n"):
                                    if line.startswith(b"id: "):
                                        eid = line[4:].decode()
                                    elif line.startswith(b"data: "):
                                        kind = json.loads(line[6:]).get(
                                            "kind"
                                        )
                                if eid is not None:
                                    probe_events.append((eid, kind))
                except (OSError, ClientError, asyncio.CancelledError):
                    pass

            probe_task = asyncio.ensure_future(resume_probe())
            tasks.append(probe_task)
            deadline = time.monotonic() + 30.0
            # enough tape that some suffix is a pure delta run
            while time.monotonic() < deadline and len(probe_events) < 6:
                await asyncio.sleep(0.25)
            if len(probe_events) < 2:
                failures.append("resume probe never accumulated events")
                raise _DrillAbort()
            if probe_events[0][1] != "full":
                failures.append("fresh stream did not start with a full frame")
            pre_kill_seq = int(probe_events[-1][0].split("-")[-1])

            def delta_resumable_ack() -> "str | None":
                """Newest event id whose entire suffix is deltas (>=1)."""
                for i in range(len(probe_events) - 2, -1, -1):
                    tail = probe_events[i + 1 :]
                    if tail and all(k == "delta" for _e, k in tail):
                        return probe_events[i][0]
                return None

            # -- phase 2: SIGKILL compose mid-storm ---------------------------
            compose_pid = sup.child_pid("compose")
            if compose_pid is None:
                failures.append("no compose child pid to kill")
                raise _DrillAbort()
            worker_pids_before = {
                n: sup.child_pid(n)
                for n in sup._info
                if n.startswith("worker-")
            }
            os.kill(compose_pid, signal.SIGKILL)
            t_kill = time.monotonic()
            stale_seen = None
            alert_seen = False
            while time.monotonic() - t_kill < 6.0:
                status, frame = await fetch_frame(session, sid=probe_sid)
                if status == 200 and frame is not None and frame.get("stale"):
                    stale_seen = time.monotonic() - t_kill
                    alert_seen = any(
                        a.get("rule") == "compose_down"
                        for a in frame.get("alerts") or []
                    )
                    break
                await asyncio.sleep(0.2)
            if stale_seen is None:
                failures.append(
                    "no stale:true /api/frame served during the compose outage"
                )
            else:
                numbers["outage_stale_after_s"] = round(stale_seen, 2)
                if not alert_seen:
                    failures.append(
                        "stale frame carried no compose_down alert"
                    )
            hz = await fetch_json(session, "/healthz")
            if not hz or hz.get("status") != "compose_down":
                failures.append(
                    f"/healthz hid the outage: {hz and hz.get('status')}"
                )
            elif hz.get("ok") is not True:
                failures.append(
                    "worker /healthz ok flapped during the outage (the "
                    "worker process is alive and serving)"
                )
            # mid-outage Last-Event-ID reconnect: a DELTA, not a re-init
            # (the probe's live connection is cut first — the scenario
            # is a viewer dropping and coming back DURING the outage)
            probe_task.cancel()
            ack_id = delta_resumable_ack()
            if ack_id is None:
                failures.append(
                    "probe tape held no delta-resumable ack "
                    f"(tape: {[k for _e, k in probe_events]})"
                )
            else:
                resumed_id, resumed = await _killall_stream_once(
                    session, base, probe_sid, last_id=ack_id
                )
                if resumed is None:
                    failures.append(
                        "mid-outage reconnect got no event at all"
                    )
                elif resumed.get("kind") != "delta":
                    failures.append(
                        "mid-outage Last-Event-ID reconnect re-inited with "
                        f"kind={resumed.get('kind')!r} instead of a delta"
                    )
            # no worker died with the compose process
            for name, pid in worker_pids_before.items():
                if sup._info[name].restarts != 0 or sup.child_pid(name) != pid:
                    failures.append(
                        f"{name} exited during the compose outage"
                    )

            # -- phase 3: compose returns -------------------------------------
            fresh_at = None
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                status, frame = await fetch_frame(session, sid=probe_sid)
                if status == 200 and frame is not None and not frame.get("stale"):
                    fresh_at = time.monotonic() - t_kill
                    break
                await asyncio.sleep(0.3)
            if fresh_at is None:
                failures.append("compose never came back with fresh frames")
                raise _DrillAbort()
            numbers["compose_restart_s"] = round(fresh_at, 2)
            post_id, post_payload = await _killall_stream_once(
                session, base, probe_sid
            )
            if post_id is None:
                failures.append("no stream event after compose restart")
            else:
                post_seq = int(post_id.split("-")[-1])
                if post_seq <= pre_kill_seq:
                    failures.append(
                        f"restarted compose re-issued old seq range "
                        f"({post_seq} <= {pre_kill_seq}) — stale acks could "
                        "alias wrong-base delta chains"
                    )
            timings = await fetch_json(session, "/api/timings")
            tsdb_stats = (timings or {}).get("tsdb") or {}
            if not tsdb_stats.get("raw_points"):
                failures.append(
                    "restarted compose did not reload the tsdb segment set"
                )
            tier = (timings or {}).get("tier") or {}
            if tier.get("restarts", 0) < 1:
                failures.append(
                    "/api/timings tier key lost the supervisor restarts"
                )

            # -- phase 4: SIGKILL a worker ------------------------------------
            victim = "worker-0"
            victim_pid = sup.child_pid(victim)
            if victim_pid is None:
                failures.append("no worker pid to kill")
                raise _DrillAbort()
            os.kill(victim_pid, signal.SIGKILL)
            served_through = 0
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                status, _frame = await fetch_frame(session, sid=probe_sid)
                if status == 200:
                    served_through += 1
                new_pid = sup.child_pid(victim)
                if (
                    sup._info[victim].restarts >= 1
                    and new_pid is not None
                    and new_pid != victim_pid
                ):
                    break
                await asyncio.sleep(0.3)
            info = sup._info[victim]
            if info.restarts < 1:
                failures.append("supervisor never restarted the killed worker")
            if info.last_exit_rc != -signal.SIGKILL:
                failures.append(
                    f"worker bookkeeping lost the exit code: "
                    f"{info.last_exit_rc!r}"
                )
            if served_through == 0:
                failures.append(
                    "/api/frame went dark while the worker restarted"
                )
            numbers["frames_served_through_worker_kill"] = served_through
            numbers["stream_events_total"] = stream_events["n"]
            if stream_events["n"] < clients:
                failures.append(
                    f"storm barely streamed: {stream_events['n']} events"
                )
    except _DrillAbort:
        pass
    finally:
        stop.set()
        if tasks:
            await asyncio.wait(tasks, timeout=10)
            for t in tasks:
                t.cancel()
        if pre_census:
            # post-storm steady state, with the client storm drained but
            # the tier still up: zero net fd/thread growth in every
            # worker that kept its pid through the kill sequence
            await _assert_no_census_growth(
                pre_census, tier_censuses, failures, numbers
            )
        await sup.stop()

    # -- phase 5+6: snapshot kill + follower catch-up (separate stores) ------
    snap = await loop.run_in_executor(None, _snapshot_kill_phase, work_dir)
    failures += snap.pop("failures")
    follower = await loop.run_in_executor(None, _follower_phase, work_dir)
    failures += follower.pop("failures")

    # -- zero unhandled exceptions in ANY process's captured logs ------------
    log_errors = await loop.run_in_executor(None, _scan_worker_logs, bus_dir)
    # the compose SIGKILL cannot produce a traceback, so anything here is
    # a genuine unhandled failure in compose/worker code under the kills
    if log_errors:
        failures.append(
            f"process logs show unhandled errors: {log_errors[0][:400]}"
        )
    return {
        "ok": not failures,
        "failures": failures,
        **numbers,
        "snapshot": snap,
        "follower": follower,
        "supervisor_restarts": sup.restarts,
    }


class _DrillAbort(Exception):
    """Internal: a phase failed in a way later phases depend on."""


# ---------------------------------------------------------------------------
# Partition drill — fleet federation under network partitions: kill /
# wedge / slow-drip / flap children mid-storm; the parent's fleet frame
# must degrade per child and never go dark (tpudash.federation).
# ---------------------------------------------------------------------------

#: partition-drill knobs: a small fast fleet.  Children refresh SLOWER
#: than the parent polls, so steady-state polls provably hit the
#: /api/summary 304 path; breaker/dwell windows sized so every state
#: transition lands inside a CI-friendly minute.
_PARTITION_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 16),
    "TPUDASH_FEDERATE_DEADLINE": ("federate_deadline", 1.0),
    "TPUDASH_FEDERATE_STALE_BUDGET": ("federate_stale_budget", 8.0),
    "TPUDASH_FEDERATE_HEDGE": ("federate_hedge", 0.3),
    "TPUDASH_BREAKER_FAILURES": ("breaker_failures", 2),
    "TPUDASH_BREAKER_COOLDOWN": ("breaker_cooldown", 2.0),
    "TPUDASH_ALERT_DWELL": ("alert_dwell", 2.0),
}

#: how much slower each child scrapes than the parent polls — the gap
#: that makes steady-state 304s deterministic rather than a timing fluke
_PARTITION_CHILD_REFRESH = 2.0


class _ChildHarness:
    """One in-process child dashboard on a FIXED local port, stoppable
    and restartable, with raw-socket stand-ins for the two partition
    shapes a stopped server can't express: ``accept-then-hang`` (the far
    process is wedged) and ``slow-drip`` (bytes trickle below any useful
    rate).  Stopping the site outright is the third shape — connection
    refused."""

    def __init__(self, name: str, port: int, cfg: Config):
        self.name = name
        self.port = port
        self.cfg = dataclasses.replace(
            cfg,
            port=port,
            refresh_interval=_PARTITION_CHILD_REFRESH,
            federate="",  # children are leaves, never parents here
        )
        self._runner = None
        self._raw_server = None
        #: the live DashboardServer while running — drills that need to
        #: drive the child's service directly (e.g. priming its tsdb for
        #: the rangescatter drill) reach it here
        self.server = None

    def _build_server(self):
        from tpudash.app.server import DashboardServer
        from tpudash.app.service import DashboardService
        from tpudash.sources.fixture import SyntheticSource

        source = SyntheticSource(
            num_chips=min(self.cfg.synthetic_chips, 64),
            generation=self.cfg.generation,
        )
        return DashboardServer(DashboardService(self.cfg, source))

    async def start(self) -> None:
        from aiohttp import web

        loop = asyncio.get_running_loop()
        # service construction does real file I/O — executor, like every
        # other drill (asynccheck rule ``async-blocking``)
        server = await loop.run_in_executor(None, self._build_server)
        self.server = server
        self._runner = web.AppRunner(server.build_app())
        await self._runner.setup()
        site = web.TCPSite(
            self._runner, "127.0.0.1", self.port, reuse_address=True
        )
        await site.start()

    async def stop(self) -> None:
        """Partition shape 1: connection refused (port closed)."""
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def start_hang(self) -> None:
        """Partition shape 2: accept-then-hang — SYN-ACK, then silence."""

        async def handler(reader, writer):
            try:
                while await reader.read(4096):
                    pass  # swallow the request; never answer
            except (OSError, asyncio.CancelledError):
                pass
            finally:
                with contextlib.suppress(OSError):
                    writer.close()

        self._raw_server = await asyncio.start_server(
            handler, "127.0.0.1", self.port, reuse_address=True
        )

    async def start_drip(self) -> None:
        """Partition shape 3: slow drip — one header byte at a time,
        far below any rate that beats the parent's deadline."""
        header = b"HTTP/1.1 200 OK\r\nContent-Length: 100000\r\n\r\n"

        async def handler(reader, writer):
            try:
                await reader.read(4096)
                for ch in header:
                    writer.write(bytes([ch]))
                    await writer.drain()
                    await asyncio.sleep(0.1)
            except (OSError, asyncio.CancelledError):
                pass
            finally:
                with contextlib.suppress(OSError):
                    writer.close()

        self._raw_server = await asyncio.start_server(
            handler, "127.0.0.1", self.port, reuse_address=True
        )

    async def stop_raw(self) -> None:
        if self._raw_server is not None:
            self._raw_server.close()
            await self._raw_server.wait_closed()
            self._raw_server = None

    async def heal(self) -> None:
        """Back to a live dashboard on the same port."""
        await self.stop_raw()
        await self.stop()
        await self.start()


def _free_ports(n: int) -> "list[int]":
    """n distinct ephemeral ports (bind-0 probe; the tiny close-to-bind
    race is acceptable in a drill, same as the storm drill)."""
    import socket as socketmod

    socks, ports = [], []
    try:
        for _ in range(n):
            s = socketmod.socket(socketmod.AF_INET, socketmod.SOCK_STREAM)
            socks.append(s)
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            with contextlib.suppress(OSError):
                s.close()
    return ports


async def run_partition_drill(
    children: int = 4, cfg: "Config | None" = None
) -> dict:
    """Fleet federation's crash-anything: K of N children are
    partitioned mid-storm — one connect-refused, one accept-then-hang,
    one slow-drip — and the drill asserts the degrade-per-child
    contract end to end:

    - the parent's ``/api/frame`` keeps answering 200 with EXACTLY the
      affected children marked stale (measured ``staleness_s``), the
      healthy child live, and ``partial: true``;
    - stale children keep serving their last-good chips until the stale
      budget expires, then go dark and their chips leave the table —
      the frame STILL serves (the healthy remainder);
    - ``child_down`` fires per affected child and ``fleet_partial``
      rides beside it; ``/healthz`` stays ``ok: true`` with truthful
      per-child status; an SSE stream keeps ticking throughout;
    - steady-state summary polls hit the ETag/304 path;
    - a child flapping with up-windows shorter than the anti-flap dwell
      pages ONCE — ``child_down`` never resolve-flaps mid-storm;
    - after heal, the fleet recovers within one poll interval (+ the
      child deadline for scheduling slack);
    - zero unhandled exceptions in the process throughout.
    """
    from aiohttp import ClientError, ClientSession, web

    children = max(4, children)
    loop = asyncio.get_running_loop()
    base_cfg = cfg or load_config()
    for env_name, (field, value) in _PARTITION_KNOBS.items():
        if not env_is_set(env_name):
            base_cfg = dataclasses.replace(base_cfg, **{field: value})
    ports = _free_ports(children + 1)
    child_ports, parent_port = ports[:children], ports[children]
    names = [f"c{i}" for i in range(children)]
    kids = [
        _ChildHarness(name, port, dataclasses.replace(base_cfg, source="synthetic"))
        for name, port in zip(names, child_ports)
    ]

    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    failures: "list[str]" = []
    numbers: dict = {"children": children}
    stream_events = {"n": 0}
    stop = asyncio.Event()
    parent_runner = None
    tasks: "list[asyncio.Task]" = []

    parent_cfg = dataclasses.replace(
        base_cfg,
        source="synthetic",  # ignored: federate wins (asserted below)
        federate=",".join(
            f"{n}=http://127.0.0.1:{p}" for n, p in zip(names, child_ports)
        ),
        host="127.0.0.1",
        port=parent_port,
    )

    def _build_parent():
        from tpudash.app.server import DashboardServer
        from tpudash.app.service import DashboardService
        from tpudash.sources import make_source

        return DashboardServer(
            DashboardService(parent_cfg, make_source(parent_cfg))
        )

    interval = parent_cfg.refresh_interval
    chips_per_child = min(base_cfg.synthetic_chips, 64)

    async def fetch_json(session, path):
        try:
            async with session.get(
                f"http://127.0.0.1:{parent_port}{path}",
                headers={"Accept-Encoding": "identity"},
            ) as r:
                return r.status, await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError, ValueError):
            return None, None

    def fed_statuses(doc) -> dict:
        return {
            n: c["status"]
            for n, c in ((doc or {}).get("federation") or {})
            .get("children", {})
            .items()
        }

    async def sse_ticker(session):
        """One long-lived fleet viewer — must keep receiving events
        through every partition (reconnect allowed; going quiet is the
        failure)."""
        while not stop.is_set():
            try:
                async with session.get(
                    f"http://127.0.0.1:{parent_port}/api/stream",
                    headers={"Accept-Encoding": "identity"},
                ) as r:
                    async for line in r.content:
                        if line.startswith(b"data:"):
                            stream_events["n"] += 1
                        if stop.is_set():
                            return
            except (OSError, ClientError, asyncio.TimeoutError):
                await asyncio.sleep(0.2)

    session = None
    try:
        for kid in kids:
            await kid.start()
        parent = await loop.run_in_executor(None, _build_parent)
        parent_runner = web.AppRunner(parent.build_app())
        await parent_runner.setup()
        await web.TCPSite(
            parent_runner, "127.0.0.1", parent_port, reuse_address=True
        ).start()

        # closed in the inner finally AFTER the client tasks are
        # cancelled — an SSE ticker outliving its session would die with
        # an unhandled "Session is closed" the zero-exception check counts
        session = ClientSession()
        try:
            # -- phase 0: fleet ready ---------------------------------------
            total = children * chips_per_child
            deadline = time.monotonic() + 60.0
            ready = False
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if (
                    status == 200
                    and frame
                    and frame.get("error") is None
                    and len(frame.get("chips") or []) == total
                    and not (frame.get("federation") or {}).get("partial")
                ):
                    ready = True
                    break
                await asyncio.sleep(0.5)
            if not ready:
                failures.append(
                    f"fleet never became ready: {status} "
                    f"{len((frame or {}).get('chips') or [])}/{total} chips"
                )
                raise _DrillAbort()
            tasks.append(asyncio.ensure_future(sse_ticker(session)))

            # -- phase 1: steady state hits the 304 path --------------------
            # children refresh every 2 s, the parent polls every 0.5 s:
            # most polls revalidate.  Wait a few intervals and read the
            # per-child counters off /healthz.
            await asyncio.sleep(6 * interval)
            _, hz = await fetch_json(session, "/healthz")
            fed = (hz or {}).get("federation") or {}
            counters = {
                n: (c.get("counters") or {})
                for n, c in (fed.get("children") or {}).items()
            }
            total_304 = sum(c.get("etag_304s", 0) for c in counters.values())
            total_fetches = sum(c.get("fetches", 0) for c in counters.values())
            numbers["steady_304s"] = total_304
            numbers["steady_fetches"] = total_fetches
            if total_304 == 0:
                failures.append(
                    "steady-state summary polls never hit the 304 path"
                )
            if not hz or hz.get("ok") is not True:
                failures.append("healthz ok flapped while healthy")
            # pre-storm steady state: the whole fleet runs in THIS
            # process (parent + child harnesses), so the census is the
            # drill process's own — the partition/heal/flap sequence
            # must hand every fd and thread back
            await warm_default_executor()
            pre_census = {
                name: fp
                for name, c in {"drill": process_census()}.items()
                for fp in (_census_fingerprint(c),)
                if fp is not None
            }

            # -- phase 2: partition 3 of N children, three shapes -----------
            refuse, hang, drip, healthy = kids[0], kids[1], kids[2], kids[3]
            await refuse.stop()          # connect refused
            await hang.stop()
            await hang.start_hang()      # accept, then silence
            await drip.stop()
            await drip.start_drip()      # bytes below any useful rate
            t_partition = time.monotonic()
            affected = {refuse.name, hang.name, drip.name}

            stale_ok = alert_ok = None
            deadline = time.monotonic() + base_cfg.federate_stale_budget - 1.0
            peak_staleness: dict = {}
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if status != 200 or not frame or frame.get("error"):
                    await asyncio.sleep(0.3)
                    continue
                st = fed_statuses(frame)
                degraded = {n for n, s in st.items() if s != "live"}
                for n, c in (frame.get("federation") or {}).get(
                    "children", {}
                ).items():
                    if c.get("staleness_s"):
                        peak_staleness[n] = max(
                            peak_staleness.get(n, 0.0), c["staleness_s"]
                        )
                if degraded and not degraded <= affected:
                    failures.append(
                        f"healthy child marked degraded: {degraded - affected}"
                    )
                    break
                rules = {
                    (a.get("rule"), a.get("chip"), a.get("state"))
                    for a in frame.get("alerts") or []
                }
                child_down_firing = {
                    chip
                    for r, chip, s in rules
                    if r == "child_down" and s == "firing"
                }
                if (
                    degraded == affected
                    and frame.get("partial") is True
                    and len(frame.get("chips") or []) == total
                ):
                    stale_ok = time.monotonic() - t_partition
                    if child_down_firing == affected and any(
                        r == "fleet_partial" for r, _c, _s in rules
                    ):
                        alert_ok = True
                        break
                await asyncio.sleep(0.3)
            if stale_ok is None:
                failures.append(
                    "frame never marked exactly the 3 partitioned children "
                    "stale while serving their last-good chips"
                )
            else:
                numbers["stale_marked_after_s"] = round(stale_ok, 2)
            if alert_ok is None and stale_ok is not None:
                failures.append(
                    "child_down×3 + fleet_partial never fired together"
                )
            _, hz = await fetch_json(session, "/healthz")
            if not hz or hz.get("ok") is not True:
                failures.append("healthz ok flapped during the partition")
            elif "degraded" not in str(hz.get("status")):
                failures.append(
                    f"healthz hid the partition: status={hz.get('status')!r}"
                )

            # -- phase 3: past the stale budget → dark, chips drop ----------
            deadline = time.monotonic() + base_cfg.federate_stale_budget + 8.0
            dark_ok = None
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if status == 200 and frame and frame.get("error") is None:
                    st = fed_statuses(frame)
                    if (
                        all(st.get(n) == "dark" for n in affected)
                        and st.get(healthy.name) == "live"
                        and len(frame.get("chips") or [])
                        == chips_per_child
                    ):
                        dark_ok = True
                        break
                await asyncio.sleep(0.4)
            if not dark_ok:
                failures.append(
                    "dark children past the stale budget never dropped to "
                    "the healthy remainder (frame must keep serving it)"
                )
            numbers["peak_staleness_s"] = {
                n: round(v, 2) for n, v in sorted(peak_staleness.items())
            }

            # -- phase 4: heal → recovery within one poll -------------------
            for kid in (refuse, hang, drip):
                await kid.heal()
            t_heal = time.monotonic()
            recovered = None
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if (
                    status == 200
                    and frame
                    and frame.get("error") is None
                    and not (frame.get("federation") or {}).get("partial")
                    and len(frame.get("chips") or []) == total
                ):
                    recovered = time.monotonic() - t_heal
                    break
                await asyncio.sleep(0.1)
            if recovered is None:
                failures.append("fleet never recovered after heal")
                raise _DrillAbort()
            numbers["recovered_after_s"] = round(recovered, 2)
            # "within one poll of heal", where "pollable" accounts for
            # the breaker: the last failed half-open probe re-opened
            # with a FRESH cooldown (+ up to 50% decorrelation jitter),
            # so worst case the child only becomes pollable
            # cooldown×1.5 after heal — then one poll (+ the deadline a
            # mid-flight poll may still burn, + scheduling slack)
            budget = (
                interval
                + base_cfg.federate_deadline
                + base_cfg.breaker_cooldown * 1.5
                + 1.5
            )
            if recovered > budget:
                failures.append(
                    f"recovery took {recovered:.2f}s "
                    f"(> {budget:.2f}s = poll + deadline + slack)"
                )

            # -- phase 5: flap vs the anti-flap dwell -----------------------
            # down-windows long enough to open the breaker (2 failed
            # polls), up-windows SHORTER than the dwell: child_down must
            # fire once and never resolve-flap until the storm ends.
            flap = kids[0]
            fired_seen = False
            resolve_flaps = 0
            flap_deadline = time.monotonic() + 3 * (1.4 + 0.6)

            async def sample_child_down() -> bool:
                _, doc = await fetch_json(session, "/api/alerts")
                return any(
                    a.get("rule") == "child_down"
                    and a.get("chip") == flap.name
                    and a.get("state") == "firing"
                    for a in (doc or {}).get("alerts") or []
                )

            async def flapper():
                for _ in range(3):
                    await flap.stop()
                    await asyncio.sleep(1.4)  # ≥2 failed polls → fires
                    await flap.heal()
                    await asyncio.sleep(0.6)  # up-window < 2 s dwell

            flap_task = asyncio.ensure_future(flapper())
            tasks.append(flap_task)
            while time.monotonic() < flap_deadline or not flap_task.done():
                firing = await sample_child_down()
                if firing:
                    fired_seen = True
                elif fired_seen:
                    resolve_flaps += 1
                    fired_seen = False
                if flap_task.done() and time.monotonic() > flap_deadline:
                    break
                await asyncio.sleep(0.15)
            await flap_task
            if not fired_seen and resolve_flaps == 0:
                failures.append("flap storm never fired child_down at all")
            if resolve_flaps > 1:
                failures.append(
                    f"child_down resolve-flapped {resolve_flaps}× through "
                    "the flap storm — the anti-flap dwell is not holding"
                )
            numbers["flap_resolve_transitions"] = resolve_flaps
            # after the storm + dwell, the alert must actually clear
            cleared = False
            deadline = time.monotonic() + base_cfg.alert_dwell + 6.0
            while time.monotonic() < deadline:
                if not await sample_child_down():
                    cleared = True
                    break
                await asyncio.sleep(0.3)
            if not cleared:
                failures.append(
                    "child_down never cleared after the flap storm + dwell"
                )

            # hedged-retry + SSE liveness bookkeeping
            _, hz = await fetch_json(session, "/healthz")
            fed = (hz or {}).get("federation") or {}
            numbers["hedges"] = sum(
                (c.get("counters") or {}).get("hedges", 0)
                for c in (fed.get("children") or {}).values()
            )
            numbers["stream_events"] = stream_events["n"]
            if stream_events["n"] < 10:
                failures.append(
                    f"fleet SSE stream barely ticked: {stream_events['n']} "
                    "events through the whole drill"
                )

            # post-storm steady state: same topology as the phase-1
            # baseline (everything healed, SSE ticker still live) —
            # zero net fd/thread growth across partition/heal/flap
            async def local_census() -> dict:
                return {"drill": process_census()}

            await _assert_no_census_growth(
                pre_census, local_census, failures, numbers
            )
        finally:
            stop.set()
            if tasks:
                await asyncio.wait(tasks, timeout=10)
                for t in tasks:
                    t.cancel()
            with contextlib.suppress(OSError):
                await session.close()
    except _DrillAbort:
        pass
    finally:
        if parent_runner is not None:
            await parent_runner.cleanup()
        for kid in kids:
            await kid.stop_raw()
            await kid.stop()
        logging.getLogger().removeHandler(trap)

    if trap.records:
        failures.append(
            f"{len(trap.records)} unhandled exception(s): "
            + trap.records[0][:500]
        )
    return {"ok": not failures, "failures": failures, **numbers}


# ---------------------------------------------------------------------------
# Cascade drill — fleets-of-fleets (PR 15): a REAL 3-level tree (root →
# mid-tier parent subprocesses → leaf dashboards); SIGKILL one mid-tier
# parent and partition one grandchild mid-storm.  The root must stay 200
# with exact per-level stale/dark sets, subtree-named alerts, and
# recover within one poll of heal.
# ---------------------------------------------------------------------------

#: cascade-drill knobs: small fast tree, breaker/dwell windows sized so
#: every transition lands inside a CI-friendly two minutes
#: the root's deadline is DELIBERATELY wider than the mids' (see
#: ``_CASCADE_MID_DEADLINE``): a mid whose own child-poll hangs answers
#: its parent only after burning its fan-in deadline, so equal deadlines
#: at every tier amplify one grandchild's tail latency into a
#: false-degraded verdict on the (healthy) mid — deadlines must shrink
#: going DOWN the tree (docs/OPERATIONS.md, topology runbook)
_CASCADE_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.5),
    "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 8),
    "TPUDASH_FEDERATE_DEADLINE": ("federate_deadline", 2.0),
    "TPUDASH_FEDERATE_STALE_BUDGET": ("federate_stale_budget", 10.0),
    "TPUDASH_FEDERATE_HEDGE": ("federate_hedge", 0.3),
    "TPUDASH_BREAKER_FAILURES": ("breaker_failures", 2),
    "TPUDASH_BREAKER_COOLDOWN": ("breaker_cooldown", 2.0),
    "TPUDASH_ALERT_DWELL": ("alert_dwell", 2.0),
}

#: mid-tier per-leaf deadline — one tier down, a fraction of the root's
_CASCADE_MID_DEADLINE = 0.6


class _MidTier:
    """One mid-tier federation parent as a REAL subprocess (``python -m
    tpudash``): the only honest way to drill a mid-tier SIGKILL.  Its
    stderr is captured for the zero-unhandled-exception verdict."""

    def __init__(self, name: str, port: int, leaf_spec: str, env: dict,
                 log_dir: str):
        self.name = name
        self.port = port
        self.leaf_spec = leaf_spec
        self.env = env
        self.log_path = os.path.join(log_dir, f"{name}.log")
        self.proc = None

    def spawn(self) -> None:
        env = dict(os.environ)
        env.update(self.env)
        env.update(
            {
                "TPUDASH_FEDERATE": self.leaf_spec,
                "TPUDASH_HOST": "127.0.0.1",
                "TPUDASH_PORT": str(self.port),
                "TPUDASH_NODE_ID": self.name,
                "JAX_PLATFORMS": "cpu",
            }
        )
        out = open(self.log_path, "ab")  # noqa: SIM115 — lives with the proc
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "tpudash"],
            env=env,
            stdout=out,
            stderr=out,
        )

    def sigkill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)

    def tracebacks(self) -> int:
        try:
            with open(self.log_path, "rb") as f:
                return f.read().count(b"Traceback (most recent call last)")
        except OSError:
            return 0


async def run_cascade_drill(
    mids: int = 4, leaves: int = 4, cfg: "Config | None" = None
) -> dict:
    """Fleets-of-fleets crash drill: a 3-level tree (1 root × ``mids``
    mid-tier parents × ``leaves`` leaf dashboards each), then — mid
    steady-state — SIGKILL one mid-tier parent AND partition one
    grandchild (accept-then-hang) under a surviving mid.  Asserted:

    - the root's ``/api/frame`` stays 200 with ``federation.depth == 2``
      and EXACT per-level accounting: the killed mid named at level 0,
      the partitioned grandchild named ``<mid>/<leaf>`` at level 1;
    - ``child_down`` fires for the killed mid and ``fleet_partial``
      names the degraded subtree; ``/healthz`` stays ``ok: true``;
    - steady-state mid→root polls ride the incremental-summary path
      (delta counters advance) and the ETag/304 path;
    - after respawn + heal the fleet is whole within one poll (+ breaker
      reopen slack) of the mid serving again;
    - zero unhandled exceptions in the root AND every mid's captured
      stderr.
    """
    from aiohttp import ClientError, ClientSession, web

    mids = max(2, mids)
    leaves = max(2, leaves)
    loop = asyncio.get_running_loop()
    base_cfg = cfg or load_config()
    for env_name, (field, value) in _CASCADE_KNOBS.items():
        if not env_is_set(env_name):
            base_cfg = dataclasses.replace(base_cfg, **{field: value})
    chips_per_leaf = min(base_cfg.synthetic_chips, 64)
    total = mids * leaves * chips_per_leaf

    ports = _free_ports(mids * leaves + mids + 1)
    leaf_ports = ports[: mids * leaves]
    mid_ports = ports[mids * leaves : mids * leaves + mids]
    root_port = ports[-1]

    # leaves live in THIS process (cheap, partitionable via raw-socket
    # shapes); mids are real subprocesses (SIGKILL-able)
    kids: "list[list[_ChildHarness]]" = []
    for i in range(mids):
        row = []
        for j in range(leaves):
            port = leaf_ports[i * leaves + j]
            row.append(
                _ChildHarness(
                    f"l{j}",
                    port,
                    dataclasses.replace(base_cfg, source="synthetic"),
                )
            )
        kids.append(row)

    mid_env = {
        env_name: str(value)
        for env_name, (_f, value) in _CASCADE_KNOBS.items()
        if env_name != "TPUDASH_REFRESH_INTERVAL"
    }
    # mids refresh faster than leaves scrape and the root polls faster
    # than mids refresh — the cadence stack that makes 304s/deltas
    # deterministic at every level; the mid deadline shrinks one tier
    # down so a hung LEAF can never burn the ROOT's deadline for a
    # healthy mid (tail-latency amplification, see _CASCADE_KNOBS)
    # tpulint: allow[env-read] writes into a CHILD process's env, no read
    mid_env["TPUDASH_REFRESH_INTERVAL"] = "1.0"
    # tpulint: allow[env-read] writes into a CHILD process's env, no read
    mid_env["TPUDASH_FEDERATE_DEADLINE"] = str(_CASCADE_MID_DEADLINE)

    log_dir = await loop.run_in_executor(
        None, functools.partial(tempfile.mkdtemp, prefix="tpudash-cascade-")
    )
    tiers = [
        _MidTier(
            f"m{i}",
            mid_ports[i],
            ",".join(
                f"l{j}=http://127.0.0.1:{kids[i][j].port}"
                for j in range(leaves)
            ),
            mid_env,
            log_dir,
        )
        for i in range(mids)
    ]

    root_cfg = dataclasses.replace(
        base_cfg,
        source="synthetic",  # ignored: federate wins
        federate=",".join(
            f"m{i}=http://127.0.0.1:{mid_ports[i]}" for i in range(mids)
        ),
        node_id="cascade-root",
        host="127.0.0.1",
        port=root_port,
    )

    def _build_root():
        from tpudash.app.server import DashboardServer
        from tpudash.app.service import DashboardService
        from tpudash.sources import make_source

        return DashboardServer(
            DashboardService(root_cfg, make_source(root_cfg))
        )

    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    failures: "list[str]" = []
    numbers: dict = {"mids": mids, "leaves_per_mid": leaves, "chips": total}
    root_runner = None
    session = None
    interval = root_cfg.refresh_interval

    async def fetch_json(session, path):
        try:
            async with session.get(
                f"http://127.0.0.1:{root_port}{path}",
                headers={"Accept-Encoding": "identity"},
            ) as r:
                return r.status, await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError, ValueError):
            return None, None

    async def mid_healthy(i) -> bool:
        try:
            async with session.get(
                f"http://127.0.0.1:{mid_ports[i]}/healthz"
            ) as r:
                return r.status == 200
        except (OSError, ClientError, asyncio.TimeoutError):
            return False

    def level_sets(frame) -> list:
        out = []
        for lvl in ((frame or {}).get("federation") or {}).get(
            "levels"
        ) or []:
            out.append(
                {
                    "stale": set(lvl.get("stale") or []),
                    "dark": set(lvl.get("dark") or []),
                    "live": lvl.get("live", 0),
                    "max_staleness_s": lvl.get("max_staleness_s") or 0.0,
                }
            )
        return out

    try:
        for row in kids:
            for kid in row:
                await kid.start()
        for tier in tiers:
            await loop.run_in_executor(None, tier.spawn)
        root = await loop.run_in_executor(None, _build_root)
        root_runner = web.AppRunner(root.build_app())
        await root_runner.setup()
        await web.TCPSite(
            root_runner, "127.0.0.1", root_port, reuse_address=True
        ).start()
        session = ClientSession()
        try:
            # -- phase 0: the whole tree converges --------------------------
            deadline = time.monotonic() + 120.0
            ready = False
            status = frame = None
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if (
                    status == 200
                    and frame
                    and frame.get("error") is None
                    and len(frame.get("chips") or []) == total
                    and not (frame.get("federation") or {}).get("partial")
                ):
                    ready = True
                    break
                await asyncio.sleep(0.5)
            if not ready:
                failures.append(
                    f"3-level fleet never converged: {status} "
                    f"{len((frame or {}).get('chips') or [])}/{total} chips "
                    f"partial={(frame or {}).get('federation', {}).get('partial')}"
                )
                raise _DrillAbort()
            fed = frame["federation"]
            if fed.get("depth") != 2:
                failures.append(f"root depth {fed.get('depth')} != 2")
            lv = level_sets(frame)
            if len(lv) < 2 or lv[0]["live"] != mids or lv[1]["live"] != mids * leaves:
                failures.append(f"level accounting wrong at steady state: {lv}")
            if not frame["chips"][0]["key"].count("/") >= 2:
                failures.append(
                    f"keys did not compose 3 levels: {frame['chips'][0]['key']}"
                )

            # -- phase 1: steady state = 304s + incremental deltas ----------
            # the stack is demand-driven: a viewer must poll the root for
            # the root to poll the mids — so the steady-state window IS a
            # polling viewer, not a sleep
            t_end = time.monotonic() + 10 * interval
            while time.monotonic() < t_end:
                await fetch_json(session, "/api/frame")
                await asyncio.sleep(interval * 0.8)
            _, hz = await fetch_json(session, "/healthz")
            counters = {
                n: (c.get("counters") or {})
                for n, c in ((hz or {}).get("federation") or {})
                .get("children", {})
                .items()
            }
            numbers["steady_304s"] = sum(
                c.get("etag_304s", 0) for c in counters.values()
            )
            numbers["delta_polls"] = sum(
                c.get("deltas", 0) for c in counters.values()
            )
            numbers["delta_bytes"] = sum(
                c.get("delta_bytes", 0) for c in counters.values()
            )
            numbers["full_bytes"] = sum(
                c.get("full_bytes", 0) for c in counters.values()
            )
            if numbers["steady_304s"] == 0:
                failures.append("root polls never hit the 304 path")
            if numbers["delta_polls"] == 0:
                failures.append(
                    "steady-state summaries never rode the incremental "
                    "delta path"
                )

            # -- phase 2: SIGKILL a mid-tier parent + partition a grandchild
            victim_mid = tiers[0]
            await loop.run_in_executor(None, victim_mid.sigkill)
            gkid = kids[1][-1]  # a grandchild under a SURVIVING mid
            await gkid.stop()
            await gkid.start_hang()
            t_fault = time.monotonic()
            subtree = f"m1/{gkid.name}"

            marked = None
            peak_levels: "dict[int, float]" = {}
            deadline = (
                time.monotonic() + base_cfg.federate_stale_budget + 14.0
            )
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if status != 200 or not frame or frame.get("error"):
                    await asyncio.sleep(0.3)
                    continue
                lv = level_sets(frame)
                for i, entry in enumerate(lv):
                    peak_levels[i] = max(
                        peak_levels.get(i, 0.0), entry["max_staleness_s"]
                    )
                if len(lv) < 2:
                    await asyncio.sleep(0.3)
                    continue
                l0_degraded = lv[0]["stale"] | lv[0]["dark"]
                l1_degraded = lv[1]["stale"] | lv[1]["dark"]
                if l0_degraded - {"m0"}:
                    failures.append(
                        f"healthy mid marked degraded: {l0_degraded}"
                    )
                    break
                if l1_degraded - {subtree} - {
                    f"m0/l{j}" for j in range(leaves)
                }:
                    # (m0's last-reported subtree may linger at level 1
                    # while m0 itself fades — that is last-known data,
                    # scoped by m0's own level-0 verdict)
                    failures.append(
                        f"wrong level-1 degraded set: {l1_degraded}"
                    )
                    break
                rules = {
                    (a.get("rule"), a.get("chip"), a.get("state"))
                    for a in frame.get("alerts") or []
                }
                fp_detail = next(
                    (
                        a.get("detail") or ""
                        for a in frame.get("alerts") or []
                        if a.get("rule") == "fleet_partial"
                    ),
                    "",
                )
                if (
                    l0_degraded == {"m0"}
                    and subtree in l1_degraded
                    and frame.get("partial") is True
                    and ("child_down", "m0", "firing") in rules
                    and subtree in fp_detail
                ):
                    marked = time.monotonic() - t_fault
                    break
                await asyncio.sleep(0.3)
            if marked is None:
                failures.append(
                    "root never marked exactly {m0} at level 0 and "
                    f"{subtree} at level 1 with child_down + subtree-named "
                    "fleet_partial"
                )
            else:
                numbers["marked_after_s"] = round(marked, 2)
            _, hz = await fetch_json(session, "/healthz")
            if not hz or hz.get("ok") is not True:
                failures.append("root healthz ok flapped during the cascade")
            elif "degraded" not in str(hz.get("status")):
                failures.append(
                    f"root healthz hid the cascade: {hz.get('status')!r}"
                )

            # -- phase 3: respawn + heal → whole within one poll ------------
            await loop.run_in_executor(None, victim_mid.spawn)
            await gkid.heal()
            serving_deadline = time.monotonic() + 90.0
            while time.monotonic() < serving_deadline:
                if await mid_healthy(0):
                    break
                await asyncio.sleep(0.5)
            t_heal = time.monotonic()
            recovered = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                status, frame = await fetch_json(session, "/api/frame")
                if (
                    status == 200
                    and frame
                    and frame.get("error") is None
                    and not (frame.get("federation") or {}).get("partial")
                    and len(frame.get("chips") or []) == total
                ):
                    recovered = time.monotonic() - t_heal
                    break
                await asyncio.sleep(0.2)
            if recovered is None:
                failures.append("fleet never became whole after heal")
            else:
                numbers["recovered_after_s"] = round(recovered, 2)
                # one poll + deadline, plus the breaker's worst-case
                # jittered reopen and the MID's own convergence on its
                # healed leaf (same budget shape, one level down)
                budget = 2 * (
                    interval
                    + base_cfg.federate_deadline
                    + base_cfg.breaker_cooldown * 1.5
                ) + 3.0
                if recovered > budget:
                    failures.append(
                        f"recovery took {recovered:.2f}s (> {budget:.2f}s)"
                    )
            numbers["peak_level_staleness_s"] = {
                f"level{i}": round(v, 2)
                for i, v in sorted(peak_levels.items())
            }
        finally:
            with contextlib.suppress(OSError):
                await session.close()
    except _DrillAbort:
        pass
    finally:
        if root_runner is not None:
            await root_runner.cleanup()
        for tier in tiers:
            tier.stop()
        for row in kids:
            for kid in row:
                await kid.stop_raw()
                await kid.stop()
        logging.getLogger().removeHandler(trap)

    if trap.records:
        failures.append(
            f"{len(trap.records)} unhandled exception(s) in the root: "
            + trap.records[0][:500]
        )
    mid_tracebacks = {t.name: t.tracebacks() for t in tiers}
    if any(mid_tracebacks.values()):
        failures.append(
            f"unhandled exceptions in mid-tier logs: {mid_tracebacks} "
            f"(logs under {log_dir})"
        )
    numbers["mid_log_dir"] = log_dir
    return {"ok": not failures, "failures": failures, **numbers}


async def run_rangescatter_drill(
    children: int = 3, cfg: "Config | None" = None
) -> dict:
    """Federated range-query drill (ISSUE 13): a parent scatters
    ``/api/range?agg=p99`` to live children, then one child is
    partitioned (accept-then-hang — the connection dies MID-QUERY) and
    the drill asserts the analytics plane's degrade contract:

    - healthy fleet: 200, ``partial: false``, every child ``ok``,
      non-empty merged series, per-child accounting present;
    - partitioned: STILL 200 within one range deadline (+ slack),
      ``partial: true``, exactly the dead child ``dark`` with an error
      and staleness accounting, the survivors ``ok``, the series still
      answering — a dark child degrades the answer, never errors it;
    - child-side ``/api/range`` revalidation: an unchanged store
      answers ``304`` to ``If-None-Match``;
    - heal: the next scatter is whole again (``partial: false``);
    - zero unhandled exceptions in any process's logs throughout.
    """
    from aiohttp import ClientSession, ClientTimeout

    children = max(2, children)
    loop = asyncio.get_running_loop()
    base_cfg = cfg or load_config()
    for env_name, (field, value) in _PARTITION_KNOBS.items():
        if not env_is_set(env_name):
            base_cfg = dataclasses.replace(base_cfg, **{field: value})
    ports = _free_ports(children + 1)
    names = [f"c{i}" for i in range(children)]
    kids = [
        _ChildHarness(
            name, port, dataclasses.replace(base_cfg, source="synthetic")
        )
        for name, port in zip(names, ports[:children])
    ]
    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    failures: "list[str]" = []
    numbers: dict = {"children": children}
    parent_runner = None
    parent_port = ports[children]
    deadline = base_cfg.range_deadline or base_cfg.federate_deadline or 1.0
    try:
        for kid in kids:
            await kid.start()
        # prime every child's tsdb: a few refresh ticks of real data so
        # the scatter has history to answer from
        for kid in kids:
            svc = kid.server.service

            def prime(s=svc):
                for _ in range(12):
                    s.render_frame()
                s.tsdb.flush(seal_partial=True)

            await loop.run_in_executor(None, prime)
        from aiohttp import web

        from tpudash.app.server import DashboardServer
        from tpudash.app.service import DashboardService
        from tpudash.sources import make_source

        parent_cfg = dataclasses.replace(
            base_cfg,
            source="synthetic",
            federate=",".join(
                f"{n}=http://127.0.0.1:{k.port}" for n, k in zip(names, kids)
            ),
            host="127.0.0.1",
            port=parent_port,
        )
        parent = await loop.run_in_executor(
            None,
            lambda: DashboardServer(
                DashboardService(parent_cfg, make_source(parent_cfg))
            ),
        )
        parent_runner = web.AppRunner(parent.build_app())
        await parent_runner.setup()
        site = web.TCPSite(
            parent_runner, "127.0.0.1", parent_port, reuse_address=True
        )
        await site.start()

        base = f"http://127.0.0.1:{parent_port}"
        params = {
            "agg": "p99",
            "cols": "tpu_tensorcore_utilization",
            "step": "60",
        }
        async with ClientSession(
            timeout=ClientTimeout(total=deadline * 6 + 10)
        ) as session:
            # phase 1: whole fleet
            t0 = time.monotonic()
            async with session.get(f"{base}/api/range", params=params) as r:
                doc = await r.json(content_type=None)
                numbers["healthy_status"] = r.status
            numbers["healthy_ms"] = round((time.monotonic() - t0) * 1e3, 1)
            fed = (doc.get("federation") or {}).get("children", {})
            if r.status != 200:
                failures.append(f"healthy scatter status {r.status}")
            if doc.get("partial"):
                failures.append("healthy fleet reported partial")
            if sorted(fed) != sorted(names):
                failures.append(f"accounting missing children: {sorted(fed)}")
            if any(c.get("status") != "ok" for c in fed.values()):
                failures.append(f"healthy child not ok: {fed}")
            if not doc.get("series", {}).get("tpu_tensorcore_utilization"):
                failures.append("healthy scatter returned no points")

            # child-side revalidation: unchanged store → 304
            child_base = f"http://127.0.0.1:{kids[0].port}"
            async with session.get(
                f"{child_base}/api/range", params=params
            ) as r1:
                etag = r1.headers.get("ETag")
                await r1.read()
            if not etag:
                failures.append("child /api/range carried no ETag")
            else:
                async with session.get(
                    f"{child_base}/api/range",
                    params=params,
                    headers={"If-None-Match": etag},
                ) as r2:
                    numbers["child_revalidate_status"] = r2.status
                    if r2.status != 304:
                        failures.append(
                            f"child revalidation answered {r2.status}, not 304"
                        )

            # phase 2: partition one child mid-query (accept-then-hang:
            # the scatter's request connects, then the bytes never come)
            victim = kids[-1]
            await victim.stop()
            await victim.start_hang()
            t0 = time.monotonic()
            async with session.get(f"{base}/api/range", params=params) as r:
                doc = await r.json(content_type=None)
                numbers["partition_status"] = r.status
            part_ms = (time.monotonic() - t0) * 1e3
            numbers["partition_ms"] = round(part_ms, 1)
            fed = (doc.get("federation") or {}).get("children", {})
            if r.status != 200:
                failures.append(f"partitioned scatter status {r.status}")
            if not doc.get("partial"):
                failures.append("partitioned fleet did not report partial")
            dark = {n for n, c in fed.items() if c.get("status") == "dark"}
            if dark != {victim.name}:
                failures.append(
                    f"dark set {sorted(dark)} != [{victim.name}]"
                )
            vc = fed.get(victim.name, {})
            if not vc.get("error"):
                failures.append("dark child carried no error detail")
            if "staleness_s" not in vc and "summary_status" not in vc:
                failures.append("dark child carried no staleness accounting")
            if any(
                c.get("status") != "ok"
                for n, c in fed.items()
                if n != victim.name
            ):
                failures.append(f"survivor not ok under partition: {fed}")
            if not doc.get("series", {}).get("tpu_tensorcore_utilization"):
                failures.append("partitioned scatter returned no points")
            # the hung child must cost ONE deadline (+ hedge + slack),
            # not wedge the query
            budget_ms = (deadline * 2 + 2.0) * 1e3
            if part_ms > budget_ms:
                failures.append(
                    f"partitioned scatter took {part_ms:.0f}ms "
                    f"(> {budget_ms:.0f}ms budget)"
                )

            # phase 3: heal → whole again
            await victim.heal()
            svc = victim.server.service

            def reprime(s=svc):
                for _ in range(6):
                    s.render_frame()
                s.tsdb.flush(seal_partial=True)

            await loop.run_in_executor(None, reprime)
            async with session.get(f"{base}/api/range", params=params) as r:
                doc = await r.json(content_type=None)
                numbers["healed_status"] = r.status
            fed = (doc.get("federation") or {}).get("children", {})
            if r.status != 200 or doc.get("partial"):
                failures.append(
                    f"healed fleet still degraded: status {r.status}, "
                    f"partial {doc.get('partial')}, {fed}"
                )
    finally:
        logging.getLogger().removeHandler(trap)
        for kid in kids:
            with contextlib.suppress(Exception):
                await kid.stop_raw()
            with contextlib.suppress(Exception):
                await kid.stop()
        if parent_runner is not None:
            with contextlib.suppress(Exception):
                await parent_runner.cleanup()
    unhandled = [
        rec for rec in trap.records
        if "Error handling request" in rec or "Traceback" in rec
    ]
    if unhandled:
        failures.append(f"unhandled exceptions in logs: {unhandled[:3]}")
    return {
        "ok": not failures,
        "failures": failures,
        **numbers,
    }


def _scan_worker_logs(bus_dir: str) -> "list[str]":
    """Unhandled-exception lines from the worker processes' captured
    stderr (the supervisor appends each worker's output to
    ``worker-<index>.log`` when log capture is on)."""
    import glob
    import os

    out = []
    for path in sorted(glob.glob(os.path.join(bus_dir, "worker-*.log"))):
        try:
            with open(path, errors="replace") as f:
                text = f.read()
        except OSError:
            continue
        for line in text.splitlines():
            if "Traceback (most recent call last)" in line or " ERROR " in line:
                out.append(f"{os.path.basename(path)}: {line.strip()}")
    return out


class DegradingChipSource:
    """Drill source: a synthetic fleet whose one chip's throughput
    metrics collapse to ``factor`` while :attr:`degraded` is set — the
    slow-chip incident the anomaly engine exists to name.  Speaks the
    ordinary MetricsSource protocol (list[Sample] passthrough)."""

    name = "degrading-synthetic"

    #: the lockstep-gating metrics a sick chip sags on.  The per-link
    #: ICI series are the reliable detection signal: SPMD lockstep makes
    #: them fleet-uniform (±2% in the synthetic model), so one sagging
    #: chip is a huge modified-z outlier — whereas utilization legit
    #: spreads across the fleet and a factor-4 sag hides in the spread
    DEGRADE_METRICS = frozenset(
        {
            schema.TENSORCORE_UTIL,
            schema.MXU_UTIL,
            schema.ICI_TX,
            schema.ICI_RX,
            *schema.ICI_LINK_SERIES.values(),
        }
    )

    def __init__(self, num_chips: int = 64, chip: int = 17, factor: float = 0.25):
        from tpudash.sources.fixture import SyntheticSource

        self.inner = SyntheticSource(num_chips=num_chips, emit_links=True)
        self.chip = int(chip)
        self.factor = float(factor)
        self.degraded = False

    def fetch(self):
        samples = self.inner.fetch()
        if not self.degraded:
            return samples
        # Sample is frozen — rebuild the sick chip's entries
        return [
            (
                dataclasses.replace(s, value=s.value * self.factor)
                if s.chip.chip_id == self.chip
                and s.metric in self.DEGRADE_METRICS
                else s
            )
            for s in samples
        ]

    def close(self) -> None:
        self.inner.close()


def make_incident_server(
    capture_path: str, chips: int = 64, cfg: "Config | None" = None
):
    """(DashboardServer, DegradingChipSource, cfg) for the incident
    drill: anomaly engine on, fast refresh, recorder capturing every
    scrape for the replay phase.  Explicit env settings win."""
    import dataclasses as _dc

    from tpudash.app.server import DashboardServer
    from tpudash.app.service import DashboardService
    from tpudash.sources.recorder import RecordingSource

    cfg = cfg or load_config()
    knobs = {
        "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.2),
        "TPUDASH_ANOMALY": ("anomaly", True),
        "TPUDASH_ANOMALY_DWELL": ("anomaly_dwell", 1.0),
        "TPUDASH_ANOMALY_SCORE_THRESHOLD": ("anomaly_score_threshold", 4.0),
        "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", max(16, chips)),
    }
    for env_name, (fieldname, value) in knobs.items():
        if not env_is_set(env_name):
            cfg = _dc.replace(cfg, **{fieldname: value})
    cfg = _dc.replace(cfg, record_path=capture_path, source="synthetic")
    # the target must exist at any --chips value (ids are 0..n-1)
    fault = DegradingChipSource(
        num_chips=cfg.synthetic_chips,
        chip=min(17, cfg.synthetic_chips - 1),
    )
    source = RecordingSource(fault, capture_path)
    return DashboardServer(DashboardService(cfg, source)), fault, cfg


async def run_incident_drill(chips: int = 64) -> dict:
    """The anomaly-layer end-to-end drill: plant a degrading chip
    mid-run, assert the ``anomaly`` alert fires within its dwell budget
    (through the webhook pager and the silences workflow), appears in
    ``/api/incidents`` with evidence resolving to a real ``/api/range``
    window, resolves after heal — then replay the recorder capture
    through the REAL CLI and assert (a) the unmodified config reproduces
    the live timeline and (b) a raised threshold counterfactually
    removes the incident from the diff."""
    import shutil
    import tempfile

    from aiohttp import ClientSession, web

    violations: list[str] = []
    loop = asyncio.get_running_loop()
    tmpdir = await loop.run_in_executor(
        None, lambda: tempfile.mkdtemp(prefix="tpudash-incident-")
    )
    capture = os.path.join(tmpdir, "capture.jsonl")

    # local webhook pager: every transition POST lands here
    webhook_hits: list[dict] = []

    async def webhook_handler(request):
        try:
            webhook_hits.append(await request.json())
        except Exception:  # noqa: BLE001 — a broken POST is a drill failure later
            webhook_hits.append({"malformed": True})
        return web.Response(text="ok")

    hook_app = web.Application()
    hook_app.router.add_post("/", webhook_handler)
    hook_runner = web.AppRunner(hook_app)
    await hook_runner.setup()
    hook_site = web.TCPSite(hook_runner, "127.0.0.1", 0)
    await hook_site.start()
    hook_port = hook_runner.addresses[0][1]

    loop = asyncio.get_running_loop()
    server, fault, cfg = await loop.run_in_executor(
        None, make_incident_server, capture, chips
    )
    import dataclasses as _dc

    server.service.cfg = cfg = _dc.replace(
        cfg, alert_webhook=f"http://127.0.0.1:{hook_port}/"
    )
    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    app = server.build_app()
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    host, port = runner.addresses[0][:2]
    base = f"http://{host}:{port}"
    target_chip = f"slice-0/{fault.chip}"
    summary: dict = {"chips": cfg.synthetic_chips, "target": target_chip}

    async def poll(session, seconds, predicate=None):
        """Drive refreshes at the drill cadence until ``predicate``
        (reading the latest /api/alerts doc) holds or time runs out.
        Returns (matched, last_alerts)."""
        deadline = time.monotonic() + seconds
        alerts: list = []
        while time.monotonic() < deadline:
            async with session.get(f"{base}/api/frame") as r:
                await r.read()
            async with session.get(f"{base}/api/alerts") as r:
                alerts = (await r.json())["alerts"]
            if predicate is not None and predicate(alerts):
                return True, alerts
            await asyncio.sleep(cfg.refresh_interval / 2)
        return predicate is None, alerts

    def anomaly_firing(alerts):
        return any(
            a["rule"] == "anomaly"
            and a["chip"] == target_chip
            and a["state"] == "firing"
            for a in alerts
        )

    live_inc = None
    try:
        async with ClientSession() as session:
            # phase 1 — healthy fleet: the engine must stay QUIET
            _, alerts = await poll(session, seconds=2.0)
            noisy = [a for a in alerts if a["rule"] == "anomaly"]
            if noisy:
                violations.append(
                    f"anomaly fired on a healthy demo fleet: {noisy[:3]}"
                )
            # phase 2 — inject the degrading chip, measure detection
            fault.degraded = True
            t_inject = time.monotonic()
            fired, alerts = await poll(session, 15.0, anomaly_firing)
            detection_s = time.monotonic() - t_inject
            summary["detection_latency_s"] = round(detection_s, 2)
            if not fired:
                violations.append(
                    "anomaly alert did not fire within 15s of the fault"
                )
            # hysteresis (straggler 3 + engine 2 cycles) + dwell budget
            budget = 8 * cfg.refresh_interval + cfg.anomaly_dwell + 2.0
            if fired and detection_s > budget:
                violations.append(
                    f"detection took {detection_s:.1f}s (budget {budget:.1f}s)"
                )
            entry = next(
                (
                    a
                    for a in alerts
                    if a["rule"] == "anomaly" and a["chip"] == target_chip
                ),
                None,
            )
            if entry is not None and not entry.get("evidence"):
                violations.append("anomaly alert carries no evidence block")
            if entry is not None and entry.get("score", 0) <= 0:
                violations.append("anomaly alert carries no score")
            # phase 3 — the incident timeline + range-window evidence
            async with session.get(f"{base}/api/incidents") as r:
                incidents = (await r.json())["incidents"]
            inc = next(
                (
                    i
                    for i in incidents
                    if i["rule"] == "anomaly" and i["chip"] == target_chip
                ),
                None,
            )
            if inc is None or inc["state"] != "open":
                violations.append(
                    f"no open anomaly incident in /api/incidents "
                    f"(got {[ (i['rule'], i['chip']) for i in incidents ]})"
                )
            else:
                summary["incident_id"] = inc["id"]
                url = inc["evidence"]["url"]
                async with session.get(f"{base}{url}") as r:
                    ok = r.status == 200
                    pts = 0
                    if ok:
                        doc = await r.json()
                        pts = sum(
                            len(v) for v in doc.get("series", {}).values()
                        )
                if not ok or pts == 0:
                    violations.append(
                        f"evidence url {url} did not resolve to range data "
                        f"(status={r.status}, points={pts})"
                    )
                summary["evidence_points"] = pts
            # phase 4 — the silences workflow: acknowledge, verify the
            # flag, verify the pager never saw the silenced window
            async with session.post(
                f"{base}/api/alerts/silence",
                json={"rule": "anomaly", "chip": target_chip, "ttl_s": 60},
            ) as r:
                if r.status != 200:
                    violations.append(f"silence POST failed: {r.status}")
            _, alerts = await poll(session, 1.0)
            sil = next(
                (
                    a
                    for a in alerts
                    if a["rule"] == "anomaly" and a["chip"] == target_chip
                ),
                None,
            )
            if sil is None or not sil.get("silenced"):
                violations.append("silenced anomaly lost its silenced flag")
            async with session.post(
                f"{base}/api/alerts/unsilence",
                json={"rule": "anomaly", "chip": target_chip},
            ) as r:
                await r.read()
            # phase 5 — heal; the alert must resolve (dwell included)
            fault.degraded = False
            t_heal = time.monotonic()
            resolved, alerts = await poll(
                session, 15.0, lambda al: not anomaly_firing(al)
            )
            summary["resolve_latency_s"] = round(
                time.monotonic() - t_heal, 2
            )
            if not resolved:
                violations.append("anomaly alert did not resolve after heal")
            async with session.get(
                f"{base}/api/incidents?state=resolved"
            ) as r:
                resolved_incs = (await r.json())["incidents"]
            live_inc = next(
                (
                    i
                    for i in resolved_incs
                    if i["rule"] == "anomaly" and i["chip"] == target_chip
                ),
                None,
            )
            if live_inc is None:
                violations.append(
                    "healed anomaly incident missing from "
                    "/api/incidents?state=resolved"
                )
            webhook_rules = {
                a["rule"]
                for hit in webhook_hits
                for a in hit.get("fired", [])
            }
            if "anomaly" not in webhook_rules:
                violations.append(
                    f"webhook pager never saw an anomaly fired transition "
                    f"(saw rules: {sorted(webhook_rules)})"
                )
    finally:
        await runner.cleanup()
        await hook_runner.cleanup()
        logging.getLogger().removeHandler(trap)

    # phase 6 — the replay twin, through the REAL CLI: the unmodified
    # config must reproduce the live timeline from the capture, and a
    # raised threshold must counterfactually remove the incident
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("TPUDASH_")
    }
    env["TPUDASH_ANOMALY"] = "1"  # tpulint: allow[env-read] child-CLI env build, not a read
    env["JAX_PLATFORMS"] = "cpu"
    proc = await asyncio.create_subprocess_exec(
        sys.executable,
        "-m",
        "tpudash.anomaly",
        "replay",
        "--capture",
        capture,
        "--threshold",
        "999",
        "--json",
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
    )
    out, err = await proc.communicate()
    replay_ok = proc.returncode == 0
    if not replay_ok:
        violations.append(
            f"replay CLI failed rc={proc.returncode}: {err.decode()[-400:]}"
        )
    else:
        try:
            doc = json.loads(out.decode())
        except ValueError:
            doc = {}
            violations.append("replay CLI emitted unparseable JSON")
        control = doc.get("control", {}).get("incidents", [])
        ctl_inc = next(
            (
                i
                for i in control
                if i["rule"] == "anomaly" and i["chip"] == target_chip
            ),
            None,
        )
        if ctl_inc is None:
            violations.append(
                "replay (unmodified config) did not reproduce the "
                "anomaly incident from the capture"
            )
        elif live_inc is not None:
            drift = abs(ctl_inc["start"] - live_inc["start"])
            summary["replay_start_drift_s"] = round(drift, 2)
            if drift > 3.0:
                violations.append(
                    f"replayed incident start drifted {drift:.1f}s from "
                    "the live timeline"
                )
        diff = doc.get("diff", {})
        removed = [
            r
            for r in diff.get("removed", [])
            if r["rule"] == "anomaly" and r["chip"] == target_chip
        ]
        if not removed:
            violations.append(
                "threshold-999 counterfactual did not remove the anomaly "
                f"incident (diff summary: {diff.get('summary')})"
            )
        summary["counterfactual_removed"] = len(removed)
    if trap.records:
        violations.append(
            f"{len(trap.records)} unhandled server error(s): "
            f"{trap.records[:3]}"
        )
    await loop.run_in_executor(
        None, lambda: shutil.rmtree(tmpdir, ignore_errors=True)
    )
    summary["webhook_posts"] = len(webhook_hits)
    summary["violations"] = violations
    summary["ok"] = not violations
    return summary


# ---------------------------------------------------------------------------
# Coldstorm drill — the cold archive tier (tpudash.tsdb.cold / compact /
# objstore) under SIGKILLs mid-compaction, torn uploads, a dark object
# store, post-verify bit rot, and a 90-day replay whose hot tiers have
# fully expired.
# ---------------------------------------------------------------------------

#: the coldstorm child: a live store with tiny segments, tiny retention,
#: and an in-process compactor folding sealed segments into archive
#: bundles at full speed.  Stamps are staged HOURS in the past so every
#: frame is expired on arrival — segment reclaim is under pressure from
#: frame 1 and must hold the verified-coverage gate while the parent's
#: SIGKILL lands mid-upload (slow object-store ops make that likely).
_COLDSTORM_CHILD = """
import sys, time, numpy as np
import tpudash.tsdb.store as storemod
storemod._SEG_MAX_BYTES = 4000  # rotate constantly: compaction folds closed files
from tpudash.tsdb import TSDB, FLEET_SERIES
from tpudash.tsdb.cold import ColdTier
from tpudash.tsdb.compact import Compactor
from tpudash.tsdb.objstore import FaultPlan, FilesystemStore
hot, obj, cache, t0 = sys.argv[1], sys.argv[2], sys.argv[3], float(sys.argv[4])
faults = FaultPlan()
faults.latency_s = 0.05  # slow object-store ops: the kill lands mid-transfer
cold = ColdTier(FilesystemStore(obj, faults=faults), cache_dir=cache,
                refresh_interval_s=0.2)
# cold is passed INTO the constructor: the load-time retention pass must
# already see the reclaim gate (expired-on-arrival segments, PR 18)
store = TSDB(path=hot, chunk_points=8, retention_raw_s=45.0,
             retention_1m_s=45.0, retention_10m_s=45.0, cold=cold)
comp = Compactor(source_dir=hot, cold=cold, interval_s=0.3)
comp.start()
keys = [f"slice-0/{i}" for i in range(8)] + [FLEET_SERIES]
cols = ["tensorcore_utilization", "hbm_usage_ratio"]
i = 0
while True:
    mat = np.full((len(keys), len(cols)), float(i % 97), dtype=np.float32)
    store.append_frame(t0 + i * 1.0, keys, cols, mat)
    store.flush()
    i += 1
"""

_COLDSTORM_LONG_S = 90 * 86400.0


def _coldstorm_verify_store(hot_dir: str, obj_dir: str) -> dict:
    """Classify every uploaded object and prove the reclaim gate held:
    each object is either a complete digest-verified bundle or an
    ignorable husk, and a segment file missing from the hot dir MUST be
    named as a source by some verified bundle — anything else is sealed
    data retired unverified (the drill's cardinal sin)."""
    import re

    from tpudash.tsdb.cold import BUNDLE_SUFFIX, BundleError, parse_bundle

    res: dict = {"bundles_verified": 0, "husks": 0, "unverified_reclaimed": []}
    verified_sources: "set[str]" = set()
    bundles_dir = os.path.join(obj_dir, "bundles")
    try:
        names = sorted(os.listdir(bundles_dir))
    except OSError:
        names = []
    for name in names:
        path = os.path.join(bundles_dir, name)
        if not name.endswith(BUNDLE_SUFFIX) or not os.path.isfile(path):
            res["husks"] += 1  # .put- temp from a killed upload
            continue
        with open(path, "rb") as fh:
            data = fh.read()
        try:
            man = parse_bundle(data)
        except BundleError:
            res["husks"] += 1  # torn upload: never registrable, never served
            continue
        res["bundles_verified"] += 1
        verified_sources.update(s["name"] for s in man.get("sources", []))
    try:
        present = {n for n in os.listdir(hot_dir) if n.endswith(".seg")}
    except OSError:
        present = set()
    # segment seqs are strictly sequential per tier: any seq below the
    # max that is absent from the hot dir was reclaimed
    by_tier: "dict[str, int]" = {}
    for n in present | verified_sources:
        m = re.match(r"(raw|1m|10m)-(\d{6})\.seg$", n)
        if m:
            by_tier[m.group(1)] = max(
                by_tier.get(m.group(1), 0), int(m.group(2))
            )
    for tier, hi in sorted(by_tier.items()):
        for seq in range(1, hi + 1):
            n = f"{tier}-{seq:06d}.seg"
            if n not in present and n not in verified_sources:
                res["unverified_reclaimed"].append(n)
    return res


def _coldstorm_next_t0(hot: str, fallback_t0: float) -> float:
    """The next append stamp (whole seconds): one past the newest raw
    record on disk, so kill rounds never duplicate stamps and the
    recovered timeline must be gap-free by construction.  The newest
    raw stamp always lives in the hot dir — the compactor never folds
    the append target."""
    from tpudash.tsdb import TSDB

    if not os.path.isdir(hot):
        return fallback_t0
    probe = TSDB(
        path=hot,
        read_only=True,
        retention_raw_s=_COLDSTORM_LONG_S,
        retention_1m_s=_COLDSTORM_LONG_S,
        retention_10m_s=_COLDSTORM_LONG_S,
    )
    pts = probe.raw_window(
        "slice-0/0",
        "tensorcore_utilization",
        int(fallback_t0 * 1000),
        int((fallback_t0 + 10 * 86400) * 1000),
    )
    probe.close()
    if not pts:
        return fallback_t0
    return pts[-1][0] // 1000 + 1.0


def _coldstorm_kill_phase(work_dir: str, kills: int = 2) -> dict:
    """SIGKILL a store+compactor process mid-upload, ``kills`` times,
    then prove (a) every object in the store is a complete verified
    bundle or an ignorable husk, (b) no segment was reclaimed without a
    verified bundle naming it as a source, and (c) a cold reopen serves
    the whole hot→cold timeline with zero duplicates and zero gaps."""
    import random

    from tpudash.tsdb import TSDB
    from tpudash.tsdb.cold import ColdTier
    from tpudash.tsdb.objstore import FilesystemStore

    hot = os.path.join(work_dir, "killstore")
    obj = os.path.join(work_dir, "killobj")
    cache = os.path.join(work_dir, "killcache")
    rng = random.Random(23)
    failures: "list[str]" = []
    stderr_tail = b""
    # staged two hours in the past: every frame is already past the
    # child's 45s retention, so reclaim pressure is constant
    first_t0 = float(int(time.time() - 7200.0))  # tpulint: allow[wall-clock] stamps staged in the expired past
    res: dict = {"bundles_verified": 0, "husks": 0, "unverified_reclaimed": []}
    for round_no in range(1, kills + 1):
        t0 = _coldstorm_next_t0(hot, first_t0)
        proc = subprocess.Popen(
            [sys.executable, "-c", _COLDSTORM_CHILD, hot, obj, cache,
             repr(t0)],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        time.sleep(2.5 + rng.random() * 1.5)
        proc.send_signal(signal.SIGKILL)
        _, err = proc.communicate()
        stderr_tail += err or b""
        res = _coldstorm_verify_store(hot, obj)
        if res["unverified_reclaimed"]:
            failures.append(
                f"round {round_no}: segment(s) reclaimed without a "
                f"verified bundle: {res['unverified_reclaimed']}"
            )
    if b"Traceback" in stderr_tail:
        failures.append(
            "coldstorm child crashed before the kill: "
            + stderr_tail.decode(errors="replace")[:300]
        )
    if res["bundles_verified"] == 0:
        failures.append(
            "no kill round ever produced a verified bundle — drill too "
            "short?"
        )
    # recovery: a fresh read-only store + a fresh cold tier over the
    # survivors must serve one contiguous second-spaced timeline
    store = TSDB(
        path=hot,
        read_only=True,
        retention_raw_s=_COLDSTORM_LONG_S,
        retention_1m_s=_COLDSTORM_LONG_S,
        retention_10m_s=_COLDSTORM_LONG_S,
    )
    cold = ColdTier(
        FilesystemStore(obj),
        cache_dir=os.path.join(work_dir, "killcache-verify"),
    )
    store.attach_cold(cold)
    pts = store.raw_window(
        "slice-0/0",
        "tensorcore_utilization",
        int(first_t0 * 1000),
        int((first_t0 + 10 * 86400) * 1000),
    )
    stamps = [p[0] for p in pts]
    dupes = len(stamps) - len(set(stamps))
    gaps = sum(1 for a, b in zip(stamps, stamps[1:]) if b - a != 1000)
    if not stamps:
        failures.append("recovered store served no raw points at all")
    if dupes:
        failures.append(
            f"{dupes} duplicate stamp(s) in the recovered hot→cold "
            "timeline (hot must win at the overlap, exactly once)"
        )
    if gaps:
        failures.append(
            f"{gaps} gap(s) in the recovered hot→cold timeline — "
            "sealed data went missing across kill + reclaim"
        )
    quarantined = cold.status()["quarantined"]
    if quarantined:
        failures.append(
            f"{quarantined} bundle(s) quarantined after clean kills — "
            "a verified upload should never rot on its own"
        )
    store.close()
    with contextlib.suppress(OSError):
        cold.close()
    return {
        "failures": failures,
        "kills": kills,
        "recovered_points": len(stamps),
        **res,
        "unverified_reclaimed": len(res["unverified_reclaimed"]),
    }


def _coldstorm_torn_phase(work_dir: str) -> dict:
    """Two torn uploads injected mid-sweep: the compactor must retry
    under its deadline, delete the torn objects, and converge to
    verified bundles — a fresh tier then serves the archive with zero
    quarantine and zero husks left behind."""
    import numpy as np

    from tpudash.tsdb import TSDB
    from tpudash.tsdb.cold import ColdTier
    from tpudash.tsdb.compact import Compactor
    from tpudash.tsdb.objstore import FaultPlan, FilesystemStore

    hot = os.path.join(work_dir, "tornstore")
    obj = os.path.join(work_dir, "tornobj")
    cache = os.path.join(work_dir, "torncache")
    failures: "list[str]" = []
    keys = [f"slice-0/{i}" for i in range(8)]
    cols = ["tensorcore_utilization", "hbm_usage_ratio"]
    store = TSDB(
        path=hot,
        chunk_points=32,
        retention_raw_s=_COLDSTORM_LONG_S,
        retention_1m_s=_COLDSTORM_LONG_S,
        retention_10m_s=_COLDSTORM_LONG_S,
    )
    t0 = float(int(time.time() - 2 * 86400.0) // 60 * 60)  # tpulint: allow[wall-clock] stamps staged 2 days back
    for i in range(120):
        mat = np.full((len(keys), len(cols)), 50.0 + i % 7, dtype=np.float32)
        store.append_frame(t0 + i * 60.0, keys, cols, mat)
    store.flush(seal_partial=True)
    store.close()
    faults = FaultPlan()
    faults.torn_puts = 2
    cold = ColdTier(FilesystemStore(obj, faults=faults), cache_dir=cache)
    comp = Compactor(
        source_dir=hot, cold=cold, include_tail=True, upload_deadline_s=30.0
    )
    summary = comp.run_once()
    with contextlib.suppress(OSError):
        comp.close()
    with contextlib.suppress(OSError):
        cold.close()
    if faults.puts_torn != 2:
        failures.append(
            f"fault hook fired {faults.puts_torn} torn put(s), wanted 2"
        )
    if summary["upload_retries"] < 2:
        failures.append(
            f"compactor retried {summary['upload_retries']} time(s) for "
            "2 torn uploads — read-back verification missed a tear"
        )
    if summary["gave_up"] or not summary["bundles_written"]:
        failures.append(
            f"sweep did not converge past the torn uploads: {summary}"
        )
    res = _coldstorm_verify_store(hot, obj)
    if res["husks"]:
        failures.append(
            f"{res['husks']} torn object(s) left in the store — the "
            "compactor must delete what read-back refused"
        )
    # a fresh tier over the healed store serves the full archive
    empty = os.path.join(work_dir, "tornempty")
    ro = TSDB(
        path=empty,
        retention_raw_s=_COLDSTORM_LONG_S,
        retention_1m_s=_COLDSTORM_LONG_S,
        retention_10m_s=_COLDSTORM_LONG_S,
    )
    cold2 = ColdTier(FilesystemStore(obj), cache_dir=cache + "-verify")
    ro.attach_cold(cold2)
    pts = ro.raw_window(
        "slice-0/0",
        "tensorcore_utilization",
        int(t0 * 1000),
        int((t0 + 120 * 60) * 1000),
    )
    if len(pts) != 120:
        failures.append(
            f"archive served {len(pts)}/120 points after the torn-upload "
            "recovery"
        )
    quarantined = cold2.status()["quarantined"]
    if quarantined:
        failures.append(
            f"{quarantined} bundle(s) quarantined after a clean recovery"
        )
    ro.close()
    with contextlib.suppress(OSError):
        cold2.close()
    return {
        "failures": failures,
        "puts_torn": faults.puts_torn,
        "upload_retries": summary["upload_retries"],
        "bundles_written": summary["bundles_written"],
        "husks": res["husks"],
        "archive_points": len(pts),
    }


def _coldstorm_dashboard_prep(work_dir: str) -> dict:
    """Stage the dashboard phase: a store of 40-day-old data (older
    than every hot retention tier, so only the archives can answer),
    compacted into bundles, then every bundle covering the first half
    of the span — across all tiers, so any tier the range query picks
    is hit — bit-flipped in the object store AFTER its upload was
    digest-verified: the post-verify bit-rot case the serving tier
    must catch at download."""
    import numpy as np

    import tpudash.tsdb.store as storemod
    from tpudash.tsdb import TSDB
    from tpudash.tsdb.cold import BundleError, ColdTier, parse_bundle
    from tpudash.tsdb.compact import Compactor
    from tpudash.tsdb.objstore import FilesystemStore

    hot = os.path.join(work_dir, "dashstore")
    obj = os.path.join(work_dir, "dashobj")
    keys = [f"slice-0/{i}" for i in range(8)]
    cols = ["tensorcore_utilization", "hbm_usage_ratio"]
    orig_seg = storemod._SEG_MAX_BYTES
    storemod._SEG_MAX_BYTES = 4000  # several raw segments -> >= 2 bundles
    try:
        store = TSDB(
            path=hot,
            chunk_points=32,
            retention_raw_s=_COLDSTORM_LONG_S,
            retention_1m_s=_COLDSTORM_LONG_S,
            retention_10m_s=_COLDSTORM_LONG_S,
        )
        t0 = float(int(time.time() - 40 * 86400.0) // 60 * 60)  # tpulint: allow[wall-clock] stamps staged 40 days back
        for i in range(240):
            mat = np.full(
                (len(keys), len(cols)), 50.0 + i % 9, dtype=np.float32
            )
            store.append_frame(t0 + i * 60.0, keys, cols, mat)
        store.flush(seal_partial=True)
        store.close()
        cold = ColdTier(
            FilesystemStore(obj), cache_dir=os.path.join(work_dir, "dashcache-prep")
        )
        comp = Compactor(
            source_dir=hot, cold=cold, include_tail=True,
            upload_deadline_s=30.0,
        )
        comp.max_bundle_bytes = 4000  # below the ctor clamp: force small bundles
        summary = comp.run_once()
        with contextlib.suppress(OSError):
            comp.close()
        with contextlib.suppress(OSError):
            cold.close()
    finally:
        storemod._SEG_MAX_BYTES = orig_seg
    if summary["gave_up"] or summary["bundles_written"] < 2:
        return {"error": f"dashboard prep did not stage bundles: {summary}"}
    # rot the FIRST HALF of the archive across every tier (whichever
    # tier the range query picks must hit a rotted bundle there), and
    # leave the second half intact — the serving contract under rot is
    # "quarantine + page + keep serving what still verifies"
    bundles_dir = os.path.join(obj, "bundles")
    mid_ms = int(t0 * 1000) + 120 * 60 * 1000
    flipped, clean = [], []
    for name in sorted(os.listdir(bundles_dir)):
        path = os.path.join(bundles_dir, name)
        try:
            with open(path, "rb") as fh:
                man = parse_bundle(fh.read())
        except BundleError as e:
            return {"error": f"prep found unreadable bundle {name}: {e}"}
        if man["t0"] >= mid_ms:
            clean.append(name)
            continue
        with open(path, "r+b") as fh:
            fh.seek(64)  # inside the first section's payload: digest must break
            byte = fh.read(1)
            fh.seek(64)
            fh.write(bytes([byte[0] ^ 0xFF]))
        flipped.append("bundles/" + name)
    if not flipped or not clean:
        return {
            "error": f"prep staged {len(flipped)} rotted / {len(clean)} "
            "clean bundle(s); the drill needs both"
        }
    return {
        "hot": hot,
        "obj": obj,
        "cache_live": os.path.join(work_dir, "dashcache-live"),
        "t0_ms": int(t0 * 1000),
        "t1_ms": int((t0 + 239 * 60) * 1000),
        "flipped": flipped,
        "clean_bundles": len(clean),
    }


async def _coldstorm_dashboard_phase(work_dir: str) -> dict:
    """The cold tier's operator surface, through a REAL dashboard over
    HTTP: a bit-rotted bundle is quarantined and paged (``cold_corrupt``)
    while the intact bundles keep serving; a dark object store degrades
    ``/api/range`` to ``partial: true`` with a ``cold_unreachable``
    alert and a truthful still-``ok`` ``/healthz``; restoring the store
    heals everything without operator action."""
    from aiohttp import ClientSession, web

    failures: "list[str]" = []
    info: dict = {}
    loop = asyncio.get_running_loop()
    prep = await loop.run_in_executor(
        None, _coldstorm_dashboard_prep, work_dir
    )
    if prep.get("error"):
        return {"failures": [prep["error"]]}
    cfg = load_config()
    knobs = {
        "TPUDASH_REFRESH_INTERVAL": ("refresh_interval", 0.2),
        "TPUDASH_SYNTHETIC_CHIPS": ("synthetic_chips", 8),
    }
    for env_name, (fieldname, value) in knobs.items():
        if not env_is_set(env_name):
            cfg = dataclasses.replace(cfg, **{fieldname: value})
    cfg = dataclasses.replace(
        cfg,
        source="synthetic",
        anomaly=False,
        tsdb_path=prep["hot"],
        # no seals during the drill: the retention pass must not race
        # the HTTP assertions (reclaim gating has its own phase + tests)
        tsdb_chunk_points=100000,
        cold_store=prep["obj"],
        cold_cache_dir=prep["cache_live"],
        cold_compact=False,
    )

    def build():
        from tpudash.app.server import DashboardServer
        from tpudash.app.service import DashboardService
        from tpudash.sources import make_source

        return DashboardServer(DashboardService(cfg, make_source(cfg)))

    server = await loop.run_in_executor(None, build)
    if server.service.cold is None:
        await loop.run_in_executor(None, server.service.close_tsdb)
        return {"failures": ["service came up without a cold tier"]}
    server.service.cold.refresh_interval_s = 0.3
    trap = _ErrorTrap()
    logging.getLogger().addHandler(trap)
    app = server.build_app()
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    host, port = runner.addresses[0][:2]
    base = f"http://{host}:{port}"
    rng_url = (
        f"{base}/api/range?chip=slice-0/0&cols=tensorcore_utilization"
        f"&start={prep['t0_ms'] / 1000.0}"
        f"&end={prep['t1_ms'] / 1000.0 + 60.0}&step=60"
    )

    def pts_of(doc):
        return sum(len(v) for v in (doc.get("series") or {}).values())

    def has_rule(alerts, rule):
        return any(a["rule"] == rule for a in alerts)

    async def poll(session, seconds, predicate):
        deadline = time.monotonic() + seconds
        last: dict = {}
        while time.monotonic() < deadline:
            async with session.get(f"{base}/api/frame") as r:
                await r.read()
            async with session.get(rng_url) as r:
                rng = (await r.json()) if r.status == 200 else {}
                rng["_status"] = r.status
            async with session.get(f"{base}/api/alerts") as r:
                alerts = (await r.json())["alerts"]
            async with session.get(f"{base}/healthz") as r:
                hz = await r.json()
            last = {"range": rng, "alerts": alerts, "healthz": hz}
            if predicate(last):
                return True, last
            await asyncio.sleep(0.25)
        return False, last

    try:
        async with ClientSession() as session:
            # phase 1 — the rotted bundle is caught at download and
            # quarantined + paged, while the intact bundles keep the
            # archive span serving (non-partial: the STORE is healthy)
            ok, snap = await poll(
                session,
                20.0,
                lambda s: s["range"].get("_status") == 200
                and pts_of(s["range"]) > 0
                and not s["range"].get("partial")
                and has_rule(s["alerts"], "cold_corrupt")
                and (s["healthz"].get("cold") or {}).get("quarantined", 0)
                >= 1,
            )
            if not ok:
                failures.append(
                    "rotted bundle was not quarantined+paged while the "
                    f"clean bundles served (last: range_status="
                    f"{snap.get('range', {}).get('_status')}, points="
                    f"{pts_of(snap.get('range', {}))}, healthz_cold="
                    f"{snap.get('healthz', {}).get('cold')})"
                )
            else:
                info["archive_points"] = pts_of(snap["range"])
                info["quarantined"] = snap["healthz"]["cold"]["quarantined"]
                detail = next(
                    (
                        a.get("detail", "")
                        for a in snap["alerts"]
                        if a["rule"] == "cold_corrupt"
                    ),
                    "",
                )
                if not any(k in detail for k in prep["flipped"]):
                    failures.append(
                        f"cold_corrupt page names none of the rotted "
                        f"bundles {prep['flipped']}: {detail!r}"
                    )
            marker_dir = os.path.join(prep["obj"], "quarantine")
            markers = (
                os.listdir(marker_dir) if os.path.isdir(marker_dir) else []
            )
            if not markers:
                failures.append(
                    "no quarantine marker persisted to the object store "
                    "— a restart would trust the rotted bundle again"
                )
            # phase 2 — dark store: range degrades to partial, the
            # pager fires, /healthz stays ok (a restart fixes nothing)
            await loop.run_in_executor(
                None, os.rename, prep["obj"], prep["obj"] + ".dark"
            )
            ok, snap = await poll(
                session,
                20.0,
                lambda s: s["range"].get("partial") is True
                and (s["range"].get("cold") or {}).get("cold_unreachable")
                and has_rule(s["alerts"], "cold_unreachable")
                and s["healthz"].get("ok") is True
                and "cold_unreachable" in str(s["healthz"].get("status")),
            )
            if not ok:
                failures.append(
                    "dark store did not degrade honestly (want "
                    "partial:true + cold_unreachable alert + ok:true "
                    f"healthz; last: partial="
                    f"{snap.get('range', {}).get('partial')}, healthz="
                    f"{snap.get('healthz', {}).get('status')})"
                )
            # phase 3 — heal: restore the store, assert everything
            # clears with NO operator action
            await loop.run_in_executor(
                None, os.rename, prep["obj"] + ".dark", prep["obj"]
            )
            ok, snap = await poll(
                session,
                20.0,
                lambda s: not s["range"].get("partial")
                and pts_of(s["range"]) > 0
                and not has_rule(s["alerts"], "cold_unreachable")
                and "cold_unreachable"
                not in str(s["healthz"].get("status")),
            )
            if not ok:
                failures.append(
                    "store heal did not clear the degrade without "
                    f"operator action (last: partial="
                    f"{snap.get('range', {}).get('partial')}, healthz="
                    f"{snap.get('healthz', {}).get('status')})"
                )
    finally:
        await runner.cleanup()  # app on_cleanup seals + closes the tsdb/cold
        logging.getLogger().removeHandler(trap)
    if trap.records:
        failures.append(
            f"{len(trap.records)} unhandled server error(s): "
            f"{trap.records[:3]}"
        )
    return {"failures": failures, **info}


def _coldstorm_replay_phase(work_dir: str) -> dict:
    """A 90-day-old incident, replayed through the REAL CLI after every
    hot tier expired AND the raw segments were deleted: the archives
    are the only copy left, and ``anomaly replay --tsdb`` must still
    reproduce the breach."""
    import shutil

    import numpy as np

    from tpudash.tsdb import TSDB
    from tpudash.tsdb.cold import ColdTier
    from tpudash.tsdb.compact import Compactor
    from tpudash.tsdb.objstore import FilesystemStore

    hot = os.path.join(work_dir, "replaystore")
    obj = os.path.join(work_dir, "replayobj")
    cache = os.path.join(work_dir, "replaycache")
    failures: "list[str]" = []
    keys = [f"slice-0/{i}" for i in range(8)]
    cols = ["tensorcore_utilization", "hbm_usage_ratio"]
    store = TSDB(
        path=hot,
        chunk_points=32,
        retention_raw_s=_COLDSTORM_LONG_S,
        retention_1m_s=_COLDSTORM_LONG_S,
        retention_10m_s=_COLDSTORM_LONG_S,
    )
    t0 = float(int(time.time() - 89 * 86400.0) // 60 * 60)  # tpulint: allow[wall-clock] incident staged 89 days back
    for i in range(180):
        mat = np.full((len(keys), len(cols)), 50.0, dtype=np.float32)
        if 60 <= i < 140:
            mat[3, 1] = 97.0  # slice-0/3 breaches hbm_usage_ratio>92
        store.append_frame(t0 + i * 60.0, keys, cols, mat)
    store.flush(seal_partial=True)
    store.close()
    cold = ColdTier(FilesystemStore(obj), cache_dir=cache)
    comp = Compactor(
        source_dir=hot, cold=cold, include_tail=True, upload_deadline_s=30.0
    )
    summary = comp.run_once()
    with contextlib.suppress(OSError):
        comp.close()
    with contextlib.suppress(OSError):
        cold.close()
    if summary["gave_up"] or not summary["bundles_written"]:
        return {"failures": [f"replay prep compaction failed: {summary}"]}
    # the point of the phase: the raw+rollup tiers are GONE — archives
    # are the only copy of the incident
    shutil.rmtree(hot)
    empty = os.path.join(work_dir, "replayempty")
    os.makedirs(empty, exist_ok=True)
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("TPUDASH_")
    }  # tpulint: allow[env-read] child-CLI env build, not a read
    env["TPUDASH_COLD_STORE"] = obj  # tpulint: allow[env-read] child-CLI env build, not a read
    env["TPUDASH_COLD_CACHE_DIR"] = cache + "-replay"  # tpulint: allow[env-read] child-CLI env build, not a read
    env["TPUDASH_ANOMALY"] = "0"  # tpulint: allow[env-read] child-CLI env build, not a read
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpudash.anomaly", "replay",
            "--tsdb", empty,
            "--start", repr(t0),
            "--end", repr(t0 + 180 * 60.0),
            "--step", "60",
            "--json",
        ],
        env=env,
        capture_output=True,
        timeout=120,
    )
    if proc.returncode != 0:
        failures.append(
            f"replay CLI failed rc={proc.returncode}: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
        return {"failures": failures}
    try:
        doc = json.loads(proc.stdout.decode())
    except ValueError:
        return {"failures": ["replay CLI emitted unparseable JSON"]}
    incidents = doc.get("variant", {}).get("incidents", [])
    hit = next(
        (
            i
            for i in incidents
            if i.get("chip") == "slice-0/3" and "hbm" in str(i.get("rule"))
        ),
        None,
    )
    if hit is None:
        failures.append(
            "replay-from-archives lost the incident (chips seen: "
            f"{sorted({str(i.get('chip')) for i in incidents})})"
        )
    return {
        "failures": failures,
        "incidents": len(incidents),
        "bundles_written": summary["bundles_written"],
    }


async def run_coldstorm_drill(kills: int = 2) -> dict:
    """The cold-tier soak: kill -9 mid-compaction (twice), a torn
    upload, a dark object store through a real HTTP dashboard, a
    digest flip, and a 90-day replay through the archives.  Exit 0 =
    every invariant held."""
    import shutil
    import tempfile

    loop = asyncio.get_running_loop()
    work_dir = await loop.run_in_executor(
        None, lambda: tempfile.mkdtemp(prefix="tpudash-coldstorm-")
    )
    failures: "list[str]" = []
    summary: dict = {"kills": kills}
    try:
        kill = await loop.run_in_executor(
            None, _coldstorm_kill_phase, work_dir, kills
        )
        failures += [f"kill: {f}" for f in kill.pop("failures")]
        summary["kill"] = kill
        torn = await loop.run_in_executor(
            None, _coldstorm_torn_phase, work_dir
        )
        failures += [f"torn: {f}" for f in torn.pop("failures")]
        summary["torn"] = torn
        dash = await _coldstorm_dashboard_phase(work_dir)
        failures += [f"dashboard: {f}" for f in dash.pop("failures")]
        summary["dashboard"] = dash
        replay = await loop.run_in_executor(
            None, _coldstorm_replay_phase, work_dir
        )
        failures += [f"replay: {f}" for f in replay.pop("failures")]
        summary["replay"] = replay
    finally:
        await loop.run_in_executor(
            None, lambda: shutil.rmtree(work_dir, ignore_errors=True)
        )
    summary["bundles_verified"] = summary.get("kill", {}).get(
        "bundles_verified", 0
    )
    summary["unverified_reclaimed"] = summary.get("kill", {}).get(
        "unverified_reclaimed", 0
    )
    summary["recovered_points"] = summary.get("kill", {}).get(
        "recovered_points", 0
    )
    summary["failures"] = failures
    summary["ok"] = not failures
    return summary


# ---------------------------------------------------------------------------
# Edgestorm drill — the edge delivery tier under kills and partitions:
# a real single-process compose publishing the TCP frame bus + N real
# edge subprocesses + a failover-streaming client population
# (tpudash.broadcast.edge).
# ---------------------------------------------------------------------------

#: edgestorm tunables, overridable from the environment.  heartbeat 1.0
#: makes the blackhole-detection budget (HEARTBEAT_MISSES * hb + 1 = 4s)
#: short enough that every partition transition lands inside a
#: CI-friendly minute; the 16-deep window at a 0.5s refresh gives every
#: failover ~8s of delta-resumable history on EVERY edge's mirror.
_EDGESTORM_KNOBS = {
    "TPUDASH_REFRESH_INTERVAL": "0.5",
    "TPUDASH_SYNTHETIC_CHIPS": "32",
    "TPUDASH_BROADCAST_WINDOW": "16",
    "TPUDASH_BUS_HEARTBEAT": "1.0",
    "TPUDASH_MAX_CONCURRENCY": "64",
    "TPUDASH_SSE_WRITE_DEADLINE": "2.0",
}

#: how long after a heal the link must be fresh again: one reconnect at
#: the worst decorrelated backoff (NET_BACKOFF_CAP=10s) + snapshot +
#: one refresh tick of slack
_EDGESTORM_HEAL_BUDGET = 15.0


class _EdgeStormProc:
    """One drill subprocess (the compose or an edge) with captured
    stdout+stderr for the zero-unhandled-exception verdict."""

    def __init__(self, name: str, module: str, env: dict, log_dir: str):
        self.name = name
        self.module = module
        self.env = env
        self.log_path = os.path.join(log_dir, f"{name}.log")
        self.proc = None

    def spawn(self) -> None:
        env = dict(os.environ)
        env.update(self.env)
        env["JAX_PLATFORMS"] = "cpu"
        out = open(self.log_path, "ab")  # noqa: SIM115 — lives with the proc
        self.proc = subprocess.Popen(
            [sys.executable, "-m", self.module],
            env=env,
            stdout=out,
            stderr=out,
        )

    def sigkill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)

    def tracebacks(self) -> "list[str]":
        try:
            with open(self.log_path, errors="replace") as f:
                text = f.read()
        except OSError:
            return []
        return [
            f"{self.name}: {line.strip()}"
            for line in text.splitlines()
            if "Traceback (most recent call last)" in line or " ERROR " in line
        ]


class _BusForwarder:
    """A drill-owned TCP forwarder between one edge and the compose bus
    — the partition switch.  ``partition()`` freezes the live pipes
    WITHOUT closing them (a blackhole: the edge must notice via its
    heartbeat budget, not a friendly RST) and stops the listener so
    reconnects get connection-refused; ``heal()`` brings the listener
    back and the edge's next retry goes through."""

    def __init__(self, listen_port: int, target_port: int):
        self.listen_port = listen_port
        self.target_port = target_port
        self._server = None
        self._pumps: "set[asyncio.Task]" = set()
        self._writers: "list" = []
        self._frozen = False

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", self.listen_port
        )

    async def _handle(self, reader, writer) -> None:
        try:
            up_r, up_w = await asyncio.open_connection(
                "127.0.0.1", self.target_port
            )
        except OSError:
            writer.close()
            return
        self._writers += [writer, up_w]

        async def pump(r, w):
            try:
                while True:
                    data = await r.read(65536)
                    if not data:
                        break
                    w.write(data)
                    await w.drain()
            except (OSError, asyncio.CancelledError):
                pass
            finally:
                # a frozen pump must NOT close its sockets — a closed
                # socket is a friendly RST, and the partition under
                # test is the silent kind only a heartbeat can see
                if not self._frozen:
                    with contextlib.suppress(OSError):
                        w.close()

        for t in (
            asyncio.ensure_future(pump(reader, up_w)),
            asyncio.ensure_future(pump(up_r, writer)),
        ):
            self._pumps.add(t)
            t.add_done_callback(self._pumps.discard)

    async def partition(self) -> None:
        self._frozen = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # cancel the pumps but leave the sockets open: bytes stop
        # flowing while TCP stays established — the silent link
        for t in list(self._pumps):
            t.cancel()
        await asyncio.sleep(0)

    async def heal(self) -> None:
        # drop the frozen carcasses; the edge has long since timed out
        for w in self._writers:
            with contextlib.suppress(OSError):
                w.close()
        self._writers = []
        self._frozen = False
        await self.start()

    async def close(self) -> None:
        await self.partition()
        for w in self._writers:
            with contextlib.suppress(OSError):
                w.close()


async def run_edgestorm_drill(
    edges: int = 16, clients: int = 256
) -> dict:
    """The edge tier's failure contract, asserted end to end — see the
    module docstring's edgestorm section for the scenario list."""
    from aiohttp import ClientError, ClientSession, TCPConnector

    edges = max(3, edges)
    clients = max(edges * 2, clients)
    _raise_fd_limit()
    loop = asyncio.get_running_loop()
    log_dir = await loop.run_in_executor(
        None, functools.partial(tempfile.mkdtemp, prefix="tpudash-edgestorm-")
    )
    ports = _free_ports(2 * edges + 2)
    compose_port, bus_port = ports[0], ports[1]
    edge_ports = ports[2 : 2 + edges]
    fwd_ports = ports[2 + edges :]
    token = "edgestorm-secret"
    knobs = {
        name: value
        for name, value in _EDGESTORM_KNOBS.items()
        if not env_is_set(name)
    }

    compose_env = dict(
        knobs,
        TPUDASH_SOURCE="synthetic",
        TPUDASH_WORKERS="0",
        TPUDASH_HOST="127.0.0.1",
        TPUDASH_PORT=str(compose_port),
        TPUDASH_BUS_LISTEN=f"127.0.0.1:{bus_port}",
        TPUDASH_BUS_TOKEN=token,
        # a PERSISTENT bus dir: the restarted compose must find the
        # epoch file and floor its seal seqs above every old event id
        TPUDASH_BROADCAST_BUS=os.path.join(log_dir, "bus"),
    )

    def edge_env(i: int) -> dict:
        return dict(
            knobs,
            TPUDASH_HOST="127.0.0.1",
            TPUDASH_PORT=str(edge_ports[i]),
            TPUDASH_WORKER_INDEX=str(i),
            TPUDASH_BUS_CONNECT=f"127.0.0.1:{fwd_ports[i]}",
            TPUDASH_BUS_TOKEN=token,
            TPUDASH_EDGE_ORIGIN=f"http://127.0.0.1:{compose_port}",
            TPUDASH_MAX_STREAMS=str(max(64, 4 * clients // edges)),
        )

    compose = _EdgeStormProc("compose", "tpudash", compose_env, log_dir)
    edge_procs = [
        _EdgeStormProc(f"edge-{i}", "tpudash.broadcast.edge", edge_env(i), log_dir)
        for i in range(edges)
    ]
    forwarders = [
        _BusForwarder(fwd_ports[i], bus_port) for i in range(edges)
    ]

    failures: "list[str]" = []
    numbers: dict = {"edges": edges, "clients": clients}
    stop = asyncio.Event()
    stats = {
        "events": 0,
        "per_edge": {p: 0 for p in edge_ports},
        "cross_resumes": 0,
        "cross_delta_resumes": 0,
        "cross_full_resumes": 0,
    }

    async def fetch_json(session, port, path):
        try:
            async with session.get(
                f"http://127.0.0.1:{port}{path}",
                headers={"Accept-Encoding": "identity"},
            ) as r:
                if r.status != 200:
                    return None
                return await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError, ValueError):
            return None

    async def fetch_frame(session, port, sid="edgestorm-probe"):
        try:
            async with session.get(
                f"http://127.0.0.1:{port}/api/frame",
                cookies={"tpudash_sid": sid},
                headers={"Accept-Encoding": "identity"},
            ) as r:
                if r.status != 200:
                    return r.status, None
                return 200, await r.json(content_type=None)
        except (OSError, ClientError, asyncio.TimeoutError):
            return None, None

    async def edge_bus(session, port) -> dict:
        doc = await fetch_json(session, port, "/healthz")
        return ((doc or {}).get("worker") or {}).get("bus") or {}

    async def edge_censuses(session) -> dict:
        """{edge-i-pid: census} for every edge still answering /healthz.
        Keyed by (index, pid) so an edge the drill SIGKILLs drops out of
        the pre/post intersection instead of being compared against its
        replacement; the compose process is killed by design too and
        carries no baseline here."""
        out: dict = {}
        for i in range(edges):
            doc = await fetch_json(session, edge_ports[i], "/healthz")
            wdoc = (doc or {}).get("worker") or {}
            if wdoc.get("pid") is not None:
                out[f"edge-{i}-pid{wdoc['pid']}"] = wdoc.get("census")
        return out

    pre_census: "dict[str, dict]" = {}

    async def storm_client(session, i):
        """One viewer pinned to an edge, failing over to the NEXT edge
        on any connection loss with its last event id — the population
        whose delta chain every kill must not break."""
        pos = i % edges
        last_id = None
        cur_port = None
        while not stop.is_set():
            port = edge_ports[pos % edges]
            try:
                hdrs = {"Accept-Encoding": "identity"}
                if last_id:
                    hdrs["Last-Event-ID"] = last_id
                async with session.get(
                    f"http://127.0.0.1:{port}/api/stream",
                    headers=hdrs,
                    cookies={"tpudash_sid": f"edgestorm-{i}"},
                ) as r:
                    if r.status != 200:
                        pos += 1
                        await asyncio.sleep(0.5)
                        continue
                    crossed = (
                        last_id is not None
                        and cur_port is not None
                        and port != cur_port
                    )
                    cur_port = port
                    buf = b""
                    async for chunk in r.content.iter_any():
                        if stop.is_set():
                            return
                        buf += chunk
                        while b"\n\n" in buf:
                            evt, buf = buf.split(b"\n\n", 1)
                            eid = kind = None
                            for line in evt.split(b"\n"):
                                if line.startswith(b"id: "):
                                    eid = line[4:].decode()
                                elif line.startswith(b"data: "):
                                    with contextlib.suppress(ValueError):
                                        kind = json.loads(line[6:]).get(
                                            "kind"
                                        )
                            if eid is None:
                                continue
                            last_id = eid
                            stats["events"] += 1
                            stats["per_edge"][port] += 1
                            if crossed and kind in ("full", "delta"):
                                # first real event after a cross-edge
                                # Last-Event-ID resume: the continuity
                                # verdict
                                stats["cross_resumes"] += 1
                                stats[f"cross_{kind}_resumes"] += 1
                                crossed = False
            except (OSError, ClientError, asyncio.TimeoutError):
                pos += 1  # fail over to the next edge
                await asyncio.sleep(0.2)

    tasks: "list[asyncio.Task]" = []
    try:
        await loop.run_in_executor(None, compose.spawn)
        for f in forwarders:
            await f.start()
        async with ClientSession(connector=TCPConnector(limit=0)) as session:
            # -- phase 0: compose + every edge ready -------------------------
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                if await fetch_json(session, compose_port, "/healthz"):
                    break
                await asyncio.sleep(0.5)
            else:
                failures.append("compose never became ready (90s)")
                raise _DrillAbort()
            for i in range(edges):
                await loop.run_in_executor(None, edge_procs[i].spawn)
            deadline = time.monotonic() + 90.0
            pending = set(range(edges))
            while time.monotonic() < deadline and pending:
                for i in list(pending):
                    bus = await edge_bus(session, edge_ports[i])
                    status, frame = await fetch_frame(session, edge_ports[i])
                    if (
                        bus.get("connected")
                        and status == 200
                        and frame is not None
                        and not frame.get("stale")
                    ):
                        pending.discard(i)
                await asyncio.sleep(0.5)
            if pending:
                failures.append(
                    f"edges never became ready (90s): {sorted(pending)}"
                )
                raise _DrillAbort()
            wdoc = await fetch_json(session, compose_port, "/api/workers")
            rows = ((wdoc or {}).get("bus") or {}).get("workers") or []
            edge_rows = [r for r in rows if r.get("role") == "edge"]
            if len(edge_rows) != edges:
                failures.append(
                    f"/api/workers shows {len(edge_rows)} edge links, "
                    f"expected {edges}"
                )
            numbers["boot_s"] = round(time.monotonic() - (deadline - 90.0), 1)
            # pre-storm steady state: every edge's census, captured
            # before the first client connects
            pre_census.update(
                {
                    name: fp
                    for name, c in (await edge_censuses(session)).items()
                    for fp in (_census_fingerprint(c),)
                    if fp is not None
                }
            )

            # -- phase 1: the storm ------------------------------------------
            tasks = [
                asyncio.ensure_future(storm_client(session, i))
                for i in range(clients)
            ]
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and stats["events"] < clients:
                await asyncio.sleep(0.5)
            if stats["events"] < clients:
                failures.append(
                    f"storm barely streamed: {stats['events']} events "
                    f"across {clients} clients"
                )
                raise _DrillAbort()

            # -- phase 2: SIGKILL an edge — resume elsewhere with deltas -----
            victims = clients // edges  # clients pinned to edge 0
            base_resumes = stats["cross_resumes"]
            await loop.run_in_executor(None, edge_procs[0].sigkill)
            deadline = time.monotonic() + 30.0
            want = base_resumes + max(1, victims // 2)
            while time.monotonic() < deadline and (
                stats["cross_resumes"] < want
            ):
                await asyncio.sleep(0.25)
            numbers["edge_kill_cross_resumes"] = (
                stats["cross_resumes"] - base_resumes
            )
            if stats["cross_resumes"] <= base_resumes:
                failures.append(
                    "no client resumed on another edge after the edge kill"
                )
            if stats["cross_delta_resumes"] == 0:
                failures.append(
                    "edge-kill failover broke delta continuity: every "
                    "cross-edge resume re-inited with a full frame"
                )
            await loop.run_in_executor(None, edge_procs[0].spawn)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                bus = await edge_bus(session, edge_ports[0])
                if bus.get("connected"):
                    break
                await asyncio.sleep(0.5)
            else:
                failures.append("respawned edge never rejoined the bus")

            # -- phase 3: partition one edge's bus link, then heal -----------
            part = 1
            await forwarders[part].partition()
            t_cut = time.monotonic()
            stale_after = None
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                status, frame = await fetch_frame(session, edge_ports[part])
                if status == 200 and frame is not None and frame.get("stale"):
                    if any(
                        a.get("rule") == "compose_down"
                        for a in frame.get("alerts") or []
                    ):
                        stale_after = time.monotonic() - t_cut
                        break
                await asyncio.sleep(0.25)
            if stale_after is None:
                failures.append(
                    "partitioned edge never served stale + compose_down"
                )
            else:
                numbers["partition_stale_after_s"] = round(stale_after, 2)
            hz = await fetch_json(session, edge_ports[part], "/healthz")
            worker = (hz or {}).get("worker") or {}
            if not hz or hz.get("ok") is not True:
                failures.append(
                    "partitioned edge /healthz flapped ok (the edge "
                    "process is alive and serving)"
                )
            if worker.get("compose_down") is not True:
                failures.append(
                    "partitioned edge /healthz hid the dead bus link"
                )
            bus = worker.get("bus") or {}
            if not (bus.get("counters") or {}).get("heartbeat_timeouts"):
                failures.append(
                    "blackholed link was not detected by heartbeat budget "
                    f"(counters: {bus.get('counters')})"
                )
            await forwarders[part].heal()
            t_heal = time.monotonic()
            healed_after = None
            deadline = time.monotonic() + _EDGESTORM_HEAL_BUDGET + 5.0
            while time.monotonic() < deadline:
                status, frame = await fetch_frame(session, edge_ports[part])
                if (
                    status == 200
                    and frame is not None
                    and not frame.get("stale")
                ):
                    healed_after = time.monotonic() - t_heal
                    break
                await asyncio.sleep(0.25)
            if healed_after is None:
                failures.append("partitioned edge never healed")
            else:
                numbers["partition_heal_s"] = round(healed_after, 2)
                if healed_after > _EDGESTORM_HEAL_BUDGET:
                    failures.append(
                        f"heal took {healed_after:.1f}s — more than one "
                        "reconnect at worst-case backoff "
                        f"({_EDGESTORM_HEAL_BUDGET}s)"
                    )

            # -- phase 4: SIGKILL the compose — lockstep degrade, epoch ------
            probe_port = edge_ports[2]
            pre_id, _pre = await _killall_stream_once(
                session, f"http://127.0.0.1:{probe_port}", "edgestorm-epoch"
            )
            if pre_id is None:
                failures.append("no stream event before the compose kill")
                raise _DrillAbort()
            pre_seq = int(pre_id.split("-")[-1])
            await loop.run_in_executor(None, compose.sigkill)
            t_kill = time.monotonic()
            degraded: "set[int]" = set()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and len(degraded) < edges:
                for i in range(edges):
                    if i in degraded:
                        continue
                    status, frame = await fetch_frame(session, edge_ports[i])
                    if (
                        status == 200
                        and frame is not None
                        and frame.get("stale")
                        and any(
                            a.get("rule") == "compose_down"
                            for a in frame.get("alerts") or []
                        )
                    ):
                        degraded.add(i)
                await asyncio.sleep(0.25)
            numbers["compose_kill_degraded_edges"] = len(degraded)
            numbers["compose_kill_lockstep_s"] = round(
                time.monotonic() - t_kill, 2
            )
            if len(degraded) < edges:
                failures.append(
                    f"only {len(degraded)}/{edges} edges degraded to "
                    "stale + compose_down during the compose outage"
                )
            await loop.run_in_executor(None, compose.spawn)
            fresh: "set[int]" = set()
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline and len(fresh) < edges:
                for i in range(edges):
                    if i in fresh:
                        continue
                    status, frame = await fetch_frame(session, edge_ports[i])
                    if (
                        status == 200
                        and frame is not None
                        and not frame.get("stale")
                    ):
                        fresh.add(i)
                await asyncio.sleep(0.5)
            if len(fresh) < edges:
                failures.append(
                    f"only {len(fresh)}/{edges} edges recovered after the "
                    "compose restart"
                )
                raise _DrillAbort()
            numbers["compose_restart_s"] = round(
                time.monotonic() - t_kill, 2
            )
            post_id, _post = await _killall_stream_once(
                session, f"http://127.0.0.1:{probe_port}", "edgestorm-epoch2"
            )
            if post_id is None:
                failures.append("no stream event after the compose restart")
            else:
                post_seq = int(post_id.split("-")[-1])
                if post_seq <= pre_seq:
                    failures.append(
                        f"restarted compose re-issued old seq range "
                        f"({post_seq} <= {pre_seq}) — resumed acks could "
                        "alias wrong-base delta chains across the restart"
                    )

            # -- phase 5: healthy links never resynced on a gap --------------
            per_edge = []
            for i in range(edges):
                bus = await edge_bus(session, edge_ports[i])
                counters = bus.get("counters") or {}
                per_edge.append(
                    {
                        "edge": i,
                        "reconnects": counters.get("reconnects", 0),
                        "resyncs": counters.get("resyncs", 0),
                        "sequence_gaps": counters.get("sequence_gaps", 0),
                        "heartbeat_timeouts": counters.get(
                            "heartbeat_timeouts", 0
                        ),
                    }
                )
                if counters.get("sequence_gaps", 0):
                    failures.append(
                        f"edge {i} hit a sequence gap on a healthy link "
                        f"(last_gap: {bus.get('last_gap')})"
                    )
                if not counters.get("resyncs", 0):
                    failures.append(
                        f"edge {i} never resynced after the compose restart"
                    )
            numbers["per_edge"] = per_edge
            numbers["stream_events_total"] = stats["events"]
            numbers["cross_resumes"] = stats["cross_resumes"]
            numbers["cross_delta_resumes"] = stats["cross_delta_resumes"]
            numbers["cross_full_resumes"] = stats["cross_full_resumes"]
            numbers["events_per_edge"] = {
                f"edge-{i}": stats["per_edge"][edge_ports[i]]
                for i in range(edges)
            }
    except _DrillAbort:
        pass
    finally:
        stop.set()
        if tasks:
            await asyncio.wait(tasks, timeout=10)
            for t in tasks:
                t.cancel()
        if pre_census:
            # post-storm steady state, storm drained but edges still up:
            # zero net fd/thread growth in every surviving edge
            async with ClientSession() as census_session:
                await _assert_no_census_growth(
                    pre_census,
                    functools.partial(edge_censuses, census_session),
                    failures,
                    numbers,
                )
        for f in forwarders:
            with contextlib.suppress(OSError):
                await f.close()
        await loop.run_in_executor(None, compose.stop)
        for p in edge_procs:
            await loop.run_in_executor(None, p.stop)

    # -- zero unhandled exceptions in ANY process's captured logs ------------
    for p in [compose] + edge_procs:
        errors = await loop.run_in_executor(None, p.tracebacks)
        if errors:
            failures.append(
                f"process logs show unhandled errors: {errors[0][:400]}"
            )
            break
    return {"ok": not failures, "failures": failures, **numbers}


def main(argv: "list[str] | None" = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tpudash.chaos",
        description="chaos drills (default: live breaker drill server)",
    )
    sub = parser.add_subparsers(dest="mode")
    ov = sub.add_parser(
        "overload", help="client-swarm overload/load-shedding soak"
    )
    ov.add_argument("--clients", type=int, default=100)
    ov.add_argument("--seconds", type=float, default=10.0)
    st = sub.add_parser(
        "storm",
        help="multi-worker SSE storm over the broadcast plane "
        "(SO_REUSEPORT worker tier + frame bus)",
    )
    st.add_argument("--clients", type=int, default=1000)
    st.add_argument("--workers", type=int, default=2)
    st.add_argument("--seconds", type=float, default=30.0)
    st.add_argument(
        "--binary-share",
        type=float,
        default=0.25,
        help="fraction of streaming clients negotiating ?format=bin "
        "(mixed JSON/binary population; 0 disables)",
    )
    ka = sub.add_parser(
        "killall",
        help="crash-anything drill: SIGKILL compose mid-storm, a worker, "
        "and a snapshotting store; verify stale degrade, restart "
        "recovery, snapshot restore-or-refuse, follower catch-up",
    )
    ka.add_argument("--clients", type=int, default=24)
    ka.add_argument("--workers", type=int, default=2)
    pa = sub.add_parser(
        "partition",
        help="fleet-federation drill: kill/wedge/slow-drip/flap children "
        "mid-storm; the parent frame must degrade per child (exact "
        "stale set, last-good serving, child_down + fleet_partial, "
        "anti-flap dwell) and recover within one poll of heal",
    )
    pa.add_argument("--children", type=int, default=4)
    ca = sub.add_parser(
        "cascade",
        help="fleets-of-fleets drill: a real 3-level tree (root × mid "
        "subprocesses × leaf dashboards); SIGKILL a mid-tier parent and "
        "partition a grandchild mid-storm — the root must stay 200 "
        "with exact per-level stale/dark accounting, subtree-named "
        "alerts, incremental-summary steady state, and recover within "
        "one poll of heal",
    )
    ca.add_argument("--mids", type=int, default=4)
    ca.add_argument("--leaves", type=int, default=4)
    es = sub.add_parser(
        "edgestorm",
        help="edge-tier drill: real compose publishing the TCP frame "
        "bus + N edge subprocesses behind partitionable forwarders; "
        "SIGKILL an edge (clients resume elsewhere with delta "
        "continuity), blackhole-partition a bus link (stale + "
        "compose_down, heals in one reconnect), SIGKILL the compose "
        "(lockstep degrade, epoch-floored resync)",
    )
    es.add_argument("--edges", type=int, default=16)
    es.add_argument("--clients", type=int, default=256)
    rs = sub.add_parser(
        "rangescatter",
        help="analytics-plane drill: federated /api/range?agg=p99 "
        "scatter-gather; partition one child mid-query and assert "
        "partial-not-error with staleness accounting, child-side "
        "ETag/304, recovery after heal",
    )
    rs.add_argument("--children", type=int, default=3)
    inc = sub.add_parser(
        "incident",
        help="anomaly-layer drill: degrading-chip fault mid-storm → "
        "anomaly alert (dwell/silences/webhook) → /api/incidents "
        "timeline with range evidence → heal → replay-CLI "
        "counterfactual under a raised threshold",
    )
    inc.add_argument("--chips", type=int, default=64)
    cs = sub.add_parser(
        "coldstorm",
        help="cold-tier drill: SIGKILL a store+compactor mid-upload "
        "(x2; zero unverified-but-reclaimed segments, zero served "
        "corrupt bundles), torn-upload retry convergence, dark object "
        "store through a real dashboard (partial:true + "
        "cold_unreachable + truthful healthz, heals without operator "
        "action), digest-flip quarantine, and a 90-day incident "
        "replayed from archives alone",
    )
    cs.add_argument("--kills", type=int, default=2)
    # internal: one shard of the storm's streaming population, spawned
    # by the storm drill itself (the load generator runs on its own
    # cores so a 2500-client storm measures the tier, not the driver)
    sc = sub.add_parser("storm-clients")
    sc.add_argument("--host", required=True)
    sc.add_argument("--port", type=int, required=True)
    sc.add_argument("--start", type=int, required=True)
    sc.add_argument("--count", type=int, required=True)
    sc.add_argument("--total", type=int, required=True)
    sc.add_argument("--ramp", type=float, required=True)
    sc.add_argument("--seconds", type=float, required=True)
    sc.add_argument("--binary-share", type=float, required=True)
    args = parser.parse_args(argv)

    configure_logging()
    if args.mode == "storm-clients":
        stats = asyncio.run(
            run_storm_client_pool(
                args.host,
                args.port,
                args.start,
                args.count,
                args.total,
                args.ramp,
                args.seconds,
                args.binary_share,
            )
        )
        print(json.dumps(stats))
        sys.exit(0)
    if args.mode == "overload":
        summary = asyncio.run(
            run_overload_drill(clients=args.clients, seconds=args.seconds)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "storm":
        summary = asyncio.run(
            run_storm_drill(
                clients=args.clients,
                workers=args.workers,
                seconds=args.seconds,
                binary_share=args.binary_share,
            )
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "killall":
        summary = asyncio.run(
            run_killall_drill(clients=args.clients, workers=args.workers)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "partition":
        summary = asyncio.run(run_partition_drill(children=args.children))
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "cascade":
        summary = asyncio.run(
            run_cascade_drill(mids=args.mids, leaves=args.leaves)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "edgestorm":
        summary = asyncio.run(
            run_edgestorm_drill(edges=args.edges, clients=args.clients)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "rangescatter":
        summary = asyncio.run(
            run_rangescatter_drill(children=args.children)
        )
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "incident":
        summary = asyncio.run(run_incident_drill(chips=args.chips))
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)
    if args.mode == "coldstorm":
        summary = asyncio.run(run_coldstorm_drill(kills=args.kills))
        print(json.dumps(summary, indent=2))
        sys.exit(0 if summary["ok"] else 1)

    from aiohttp import web

    app, cfg = make_chaos_app()
    log.info(
        "chaos drill on :%d — endpoints %s; watch /healthz "
        "source_health.endpoints for breaker transitions",
        cfg.port,
        ", ".join(DEFAULT_DRILL),
    )
    web.run_app(app, host=cfg.host, port=cfg.port)  # pragma: no cover


if __name__ == "__main__":  # pragma: no cover
    main()
