"""``python -m tpudash.demo`` — the whole stack in one process.

Zero-to-aha entry point: starts the node exporter (on-chip probe source
when a chip is present, synthetic otherwise) on ``:9100`` and the
dashboard scraping it on ``:8050``, in one asyncio loop.  What the
reference needed a cluster, a Prometheus server, and an out-of-repo
exporter to show, this shows with one command on a TPU VM — or on a
laptop with ``TPUDASH_DEMO_SOURCE=synthetic``.

    python -m tpudash.demo            # probe the local chip(s)
    TPUDASH_DEMO_SOURCE=synthetic python -m tpudash.demo
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging

from aiohttp import web

from tpudash.config import (
    Config,
    configure_logging,
    env_is_set,
    env_read,
    load_config,
)

log = logging.getLogger(__name__)


def demo_configs(cfg: Config | None = None) -> tuple[Config, Config]:
    """(exporter_cfg, dashboard_cfg) for the single-process demo."""
    cfg = cfg or load_config()
    exporter_source = env_read("TPUDASH_DEMO_SOURCE")
    if not exporter_source:
        try:
            import jax

            exporter_source = (
                "probe" if jax.devices()[0].platform == "tpu" else "synthetic"
            )
        except Exception:  # noqa: BLE001 — no jax → synthetic demo
            exporter_source = "synthetic"
    exporter_cfg = dataclasses.replace(cfg, source=exporter_source)
    if (
        exporter_source == "synthetic"
        and exporter_cfg.synthetic_links
        and not exporter_cfg.synthetic_cold_links
        and not env_is_set("TPUDASH_SYNTHETIC_COLD_LINKS")
    ):
        # zero-to-aha includes the failing-cable story: one injected cold
        # link so the coldest-link panel, the link-straggler banner, and
        # the drill-down link table all show something on first run
        chip = min(17, max(0, exporter_cfg.synthetic_chips - 1))
        exporter_cfg = dataclasses.replace(
            exporter_cfg, synthetic_cold_links=f"{chip}:xn"
        )
    # scrape address must match the exporter's bind: loopback works for
    # the wildcard bind, a specific TPUDASH_HOST needs that address
    scrape_host = "127.0.0.1" if cfg.host in ("0.0.0.0", "::") else cfg.host
    dash_cfg = dataclasses.replace(
        cfg,
        source="scrape",
        scrape_url=f"http://{scrape_host}:{cfg.exporter_port}/metrics",
    )
    return exporter_cfg, dash_cfg


async def start_demo(cfg: Config | None = None) -> "tuple[web.AppRunner, web.AppRunner]":
    """Start both servers; returns their runners (caller cleans up)."""
    from tpudash.app.server import make_app as make_dash_app
    from tpudash.exporter.server import make_app as make_exporter_app

    exporter_cfg, dash_cfg = demo_configs(cfg)

    # app construction runs in the executor: DashboardService.__init__
    # restores checkpoints/history from disk and sources open HTTP
    # sessions — startup I/O that must not run on the serving loop
    # (asynccheck rule ``async-blocking``)
    loop = asyncio.get_running_loop()
    exporter_app = await loop.run_in_executor(
        None, make_exporter_app, exporter_cfg
    )
    exporter_runner = web.AppRunner(exporter_app)
    await exporter_runner.setup()
    try:
        await web.TCPSite(
            exporter_runner, exporter_cfg.host, exporter_cfg.exporter_port
        ).start()
    except Exception:
        await exporter_runner.cleanup()  # setup() ran on_startup hooks
        raise
    log.info(
        "exporter (%s source) on :%d/metrics",
        exporter_cfg.source,
        exporter_cfg.exporter_port,
    )

    # don't leak sockets when the dashboard can't start (e.g. its port is
    # taken) — the caller never gets handles, so everything already live
    # (the exporter, and the dash runner once set up) is cleaned here.
    # cleanup failures are suppressed so the ORIGINAL error (which port,
    # what failed) propagates, and one failed cleanup can't skip the next
    try:
        dash_app = await loop.run_in_executor(None, make_dash_app, dash_cfg)
        dash_runner = web.AppRunner(dash_app)
        await dash_runner.setup()
    except Exception:
        with contextlib.suppress(Exception):
            await exporter_runner.cleanup()
        raise
    try:
        await web.TCPSite(dash_runner, dash_cfg.host, dash_cfg.port).start()
    except Exception:
        with contextlib.suppress(Exception):
            await dash_runner.cleanup()
        with contextlib.suppress(Exception):
            await exporter_runner.cleanup()
        raise
    log.info("dashboard on :%d (scraping the exporter)", dash_cfg.port)
    return exporter_runner, dash_runner


async def _main() -> None:  # pragma: no cover - blocking entry
    runners = await start_demo()
    try:
        await asyncio.Event().wait()  # serve until interrupted
    finally:
        for r in runners:
            await r.cleanup()


if __name__ == "__main__":  # pragma: no cover
    from tpudash.parallel.distributed import maybe_initialize

    configure_logging()  # first, so the rendezvous outcome is visible
    maybe_initialize()  # before demo_configs queries jax.devices()
    asyncio.run(_main())
