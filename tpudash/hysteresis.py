"""Consecutive-breach hysteresis shared by the alert engine and the
straggler detector.

Both subsystems run the same per-(rule, chip) state machine on every
frame: ok → pending (breaching, streak < for_cycles) → firing; any
non-breaching frame resets to ok, and keys not seen this frame resolve
implicitly (the chip left the table or recovered).  One implementation
here so the semantics cannot silently diverge.

:class:`DwellSet` is the resolve-side twin: ``for_cycles`` debounces the
FIRING edge, the dwell debounces the RESOLVE edge.  Synthesized alerts
(``endpoint_down``, ``child_down``, ``compose_down``, ``fleet_partial``)
fire from binary conditions — a breaker state, a bus link — that can
flap at sub-poll period, and the webhook pager fires on every
transition: without a dwell, one flapping federated child pages the
on-call once per flap.  With it, a fired alert keeps reporting
``firing`` (flagged ``dwell: true``) until the condition has stayed
clear for ``dwell_s`` seconds, collapsing a flap storm into one page and
one resolve.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Track:
    streak: int = 0
    firing_since: float | None = None
    last_value: float = 0.0


@dataclass
class TrackSet:
    """Streak bookkeeping over (rule, chip)-style keys."""

    _tracks: dict = field(default_factory=dict)

    def hit(self, key, for_cycles: int, now: float) -> "tuple[Track, bool]":
        """Record one breaching frame for ``key``; returns the track and
        whether it has reached the firing state (stamping firing_since on
        the transition)."""
        track = self._tracks.get(key)
        if track is None:
            track = self._tracks[key] = Track()
        track.streak += 1
        firing = track.streak >= for_cycles
        if firing and track.firing_since is None:
            track.firing_since = now
        return track, firing

    def resolve_unseen(self, seen: set) -> None:
        """Drop every key not breaching this frame — its streak restarts
        from zero on the next breach."""
        for key in list(self._tracks):
            if key not in seen:
                del self._tracks[key]

    def items(self):
        return self._tracks.items()

    def __len__(self) -> int:
        return len(self._tracks)


@dataclass
class _Dwell:
    entry: dict          # the last FIRING alert entry for this key
    last_firing: float   # monotonic stamp of the last firing update


@dataclass
class DwellSet:
    """Anti-flap resolve dwell over synthesized-alert entries.

    ``apply(entries, now)`` takes the alert entries a synthesis site just
    built (AlertEngine output shape, keyed by ``(rule, chip)``) and
    returns them with held entries appended: a key that was firing
    recently but produced no firing entry this cycle is re-emitted as a
    copy of its last firing entry, flagged ``dwell: true``, until the
    condition has stayed clear for ``dwell_s`` seconds.  ``dwell_s <= 0``
    is a transparent pass-through (the shipped default — operators opt
    in; the federation drill and runbook set it).

    Timing is monotonic (the clock is injectable for tests): a wall-clock
    step must neither instantly expire a dwell nor pin one forever.
    """

    dwell_s: float = 0.0
    clock: "object" = time.monotonic
    _held: dict = field(default_factory=dict)

    def apply(self, entries: "list[dict]", now: "float | None" = None) -> "list[dict]":
        if self.dwell_s <= 0:
            return entries
        now = float(self.clock()) if now is None else float(now)
        firing_keys = set()
        for e in entries:
            key = (e.get("rule"), e.get("chip"))
            if e.get("state") == "firing":
                firing_keys.add(key)
                # keep a copy: the held re-emission must not alias an
                # entry later cycles mutate (silence annotation stamps
                # entries in place)
                self._held[key] = _Dwell(entry=dict(e), last_firing=now)
        out = list(entries)
        present = {(e.get("rule"), e.get("chip")) for e in entries}
        for key in list(self._held):
            if key in firing_keys:
                continue
            dw = self._held[key]
            if now - dw.last_firing >= self.dwell_s:
                del self._held[key]
                continue
            if key in present:
                # demoted to pending this cycle (e.g. breaker half-open
                # mid-recovery): the dwell upgrades it back to firing so
                # the pager sees no resolve yet — replace, don't duplicate
                out = [
                    e
                    for e in out
                    if (e.get("rule"), e.get("chip")) != key
                ]
            held = dict(dw.entry)
            held["state"] = "firing"
            held["dwell"] = True
            held["detail"] = (
                (held.get("detail") or "")
                + f" [recovering: held by {self.dwell_s:g}s anti-flap dwell]"
            ).strip()
            out.append(held)
        return out

    def __len__(self) -> int:
        return len(self._held)
