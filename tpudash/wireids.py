"""Every on-wire identifier tpudash owns, in one importable table.

PR 12 renumbered the sketch segment record 3→4 by hand after discovering
snapshot.py had already spent 3 on its MANIFEST record inside the shared
TSB1 framing — the collision survived review because each module declared
its constants locally.  This module makes that class of bug impossible:

- every wire-visible identifier (TDB1 frame kinds, TSB1 record types,
  TE stream event types, bus protocol versions, container magics) is
  DECLARED here and imported by the module that uses it;
- the tables are built through :func:`_freeze`, which raises at import
  time on a duplicate id — a collision fails every test run and CI job
  before a single byte is written;
- boundcheck's ``wire-id-unregistered`` rule fails the static-analysis
  gate on any new module-level integer assignment to a wire-id-shaped
  name (``KIND_*`` / ``_REC_*`` / ``PROTO`` / ``EVT_*``) outside this
  module, so new identifiers cannot bypass the registry.

Retired identifiers stay registered: the id is still spent (an old
document may carry it and must refuse loudly, not be misparsed by a
reassigned meaning).
"""

from __future__ import annotations

# -- TDB1: browser/parent frame container (tpudash/app/wire.py) --------------
TDB1_MAGIC = b"TDB1"
TDB1_VERSION = 1

TDB1_KIND_DELTA = 1
#: retired in PR 11 (full frame with inline figure JSON); the id stays
#: spent so an old document refuses instead of misparsing
TDB1_KIND_FULL_RETIRED = 2
TDB1_KIND_SUMMARY = 3
TDB1_KIND_TEMPLATE = 4
TDB1_KIND_CFULL = 5
TDB1_KIND_FULLC = 6
TDB1_KIND_SUMMARY_DELTA = 7

# -- TE: binary stream event framing (tpudash/app/wire.py) -------------------
TE_MAGIC = b"TE"

TE_EVT_FULL = 1
TE_EVT_DELTA = 2
TE_EVT_KEEPALIVE = 3
TE_EVT_TEMPLATE = 4

# -- TSB1: tsdb segment/snapshot/bundle record framing -----------------------
# (tpudash/tsdb/store.py, snapshot.py, cold.py, follower.py — one shared
# frame header, record types globally unique across all three file kinds
# so any tool dispatches on type alone, whichever file it is reading)
TSB1_MAGIC = b"TSB1"

TSB1_REC_BLOCK = 1
TSB1_REC_ROLLUP = 2
TSB1_REC_SNAPSHOT_MANIFEST = 3
TSB1_REC_SKETCH = 4
TSB1_REC_BUNDLE_MANIFEST = 5

#: cold-bundle footer magic (tpudash/tsdb/cold.py)
TDBF_FOOTER_MAGIC = b"TDBF"

# -- bus: seal replication protocol (tpudash/broadcast/bus.py) ---------------
BUS_PREAMBLE_MAGIC = b"TDRP"
#: bump on any incompatible wire change — a version-skewed worker must
#: fail its handshake loudly, not misparse seals quietly
BUS_PROTO = 4
#: protocols a mirror accepts from a publisher (4 is additive over 3)
BUS_PROTO_COMPAT = frozenset({3, BUS_PROTO})


def _freeze(pairs, label: str) -> "dict[int, str]":
    """id → name table that refuses duplicates at import time."""
    table: "dict[int, str]" = {}
    for value, name in pairs:
        value = int(value)
        if value in table:
            raise ValueError(
                f"duplicate {label} id {value}: "
                f"{table[value]!r} vs {name!r}"
            )
        table[value] = name
    return table


TDB1_KINDS = _freeze(
    (
        (TDB1_KIND_DELTA, "delta"),
        (TDB1_KIND_FULL_RETIRED, "full (retired)"),
        (TDB1_KIND_SUMMARY, "summary"),
        (TDB1_KIND_TEMPLATE, "template"),
        (TDB1_KIND_CFULL, "cfull"),
        (TDB1_KIND_FULLC, "fullc"),
        (TDB1_KIND_SUMMARY_DELTA, "summary-delta"),
    ),
    "TDB1 kind",
)

TE_EVENT_TYPES = _freeze(
    (
        (TE_EVT_FULL, "full"),
        (TE_EVT_DELTA, "delta"),
        (TE_EVT_KEEPALIVE, "keepalive"),
        (TE_EVT_TEMPLATE, "template"),
    ),
    "TE event type",
)

TSB1_RECORD_TYPES = _freeze(
    (
        (TSB1_REC_BLOCK, "block"),
        (TSB1_REC_ROLLUP, "rollup"),
        (TSB1_REC_SNAPSHOT_MANIFEST, "snapshot manifest"),
        (TSB1_REC_SKETCH, "sketch"),
        (TSB1_REC_BUNDLE_MANIFEST, "bundle manifest"),
    ),
    "TSB1 record type",
)

BUS_PROTOS = _freeze(
    (
        (3, "fd-passing preamble, ring descriptors, template delivery"),
        (BUS_PROTO, "network TCP/TLS transport, hellos, heartbeats"),
    ),
    "bus protocol",
)

#: container magics must also stay distinct — a TSB1 segment fed to the
#: TDB1 splitter (or vice versa) refuses on magic, never misparses
_MAGICS = _freeze(
    (
        (int.from_bytes(TDB1_MAGIC, "little"), "TDB1"),
        (int.from_bytes(TSB1_MAGIC, "little"), "TSB1"),
        (int.from_bytes(TDBF_FOOTER_MAGIC, "little"), "TDBF"),
        (int.from_bytes(BUS_PREAMBLE_MAGIC, "little"), "TDRP"),
        (int.from_bytes(TE_MAGIC, "little"), "TE"),
    ),
    "container magic",
)
