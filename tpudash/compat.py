"""Real-world metric-name compatibility: GKE tpu-device-plugin & libtpu.

The dashboard's canonical ``tpu_*`` schema (tpudash.schema) is what the
in-repo exporter emits — but a real GKE cluster's scrape surface speaks
different dialects.  This module is the single source of truth mapping those
dialects onto the canonical schema, playing the role the reference plays by
consuming the real ``amd_gpu_*`` series and ``gpu_id``/``card_model`` labels
of an exporter it does not control (reference app.py:167-201).

Supported dialects (series names AND label sets):

1. **GKE tpu-device-plugin metrics server** (DaemonSet, ``:2112/metrics``;
   surfaced in Cloud Monitoring as ``kubernetes.io/node/accelerator/*``):
   series ``duty_cycle``, ``memory_used``, ``memory_total``,
   ``tensorcore_utilization``, ``memory_bandwidth_utilization`` with labels
   ``accelerator_id="<board-id>-<chip-index>"``, ``make="cloud-tpu"``,
   ``model="tpu-v5-lite-podslice"``, ``tpu_topology="2x4"`` — plus the
   managed-collection target labels (``instance``, ``pod``, ``namespace``,
   ``node``, ...).  The Cloud-Monitoring-prefixed PromQL forms
   (``kubernetes_io:node_accelerator_duty_cycle`` ...) are accepted too.

2. **libtpu runtime metrics / tpu-monitoring-library** (the series behind
   ``tpu-info``): dotted metric ids ``tpu.runtime.tensorcore.dutycycle.percent``,
   ``tpu.runtime.hbm.memory.usage.bytes``, ``tpu.runtime.hbm.memory.total.bytes``
   and their Prometheus-sanitized underscore forms, plus the short
   monitoring-library names ``duty_cycle_pct``, ``tensorcore_util``,
   ``hbm_capacity_usage``, ``hbm_capacity_total``.

Alias resolution happens at parse time in BOTH the pure-Python parsers
(sources/base.py, exporter/textfmt.py) and the native C++ kernel — the C++
table is *generated from this module* (see ``native_alias_table``) so the two
paths cannot drift; tests/test_compat.py holds differential coverage.

Chip identity for dialect (1): GKE exposes no integer ``chip_id`` label —
the chip is the ``<index>`` suffix of ``accelerator_id`` and the board/node
id prefix scopes it.  When no explicit ``slice`` label exists, the prefix
becomes the slice id, so multi-node scrapes (same chip indices on every
node) stay collision-free and group by board.
"""

from __future__ import annotations

import re

from tpudash import schema

#: Foreign (real-world) series name → canonical tpudash series.
SERIES_ALIASES: dict[str, str] = {
    # --- GKE tpu-device-plugin metrics server (:2112/metrics) ---------------
    "duty_cycle": schema.TENSORCORE_UTIL,
    "memory_used": schema.HBM_USED,
    "memory_total": schema.HBM_TOTAL,
    "tensorcore_utilization": schema.MXU_UTIL,
    "memory_bandwidth_utilization": schema.MEMBW_UTIL,
    # Cloud Monitoring prefixed PromQL forms of the same series
    "kubernetes_io:node_accelerator_duty_cycle": schema.TENSORCORE_UTIL,
    "kubernetes_io:node_accelerator_memory_used": schema.HBM_USED,
    "kubernetes_io:node_accelerator_memory_total": schema.HBM_TOTAL,
    "kubernetes_io:node_accelerator_tensorcore_utilization": schema.MXU_UTIL,
    "kubernetes_io:node_accelerator_memory_bandwidth_utilization": schema.MEMBW_UTIL,
    # --- libtpu runtime metrics (tpu-monitoring-library / tpu-info) ---------
    "tpu.runtime.tensorcore.dutycycle.percent": schema.TENSORCORE_UTIL,
    "tpu_runtime_tensorcore_dutycycle_percent": schema.TENSORCORE_UTIL,
    "tpu.runtime.hbm.memory.usage.bytes": schema.HBM_USED,
    "tpu_runtime_hbm_memory_usage_bytes": schema.HBM_USED,
    "tpu.runtime.hbm.memory.total.bytes": schema.HBM_TOTAL,
    "tpu_runtime_hbm_memory_total_bytes": schema.HBM_TOTAL,
    # short monitoring-library metric ids
    "duty_cycle_pct": schema.TENSORCORE_UTIL,
    "tensorcore_util": schema.MXU_UTIL,
    "hbm_capacity_usage": schema.HBM_USED,
    "hbm_capacity_total": schema.HBM_TOTAL,
}


def canonical_series(name: str) -> str:
    """Canonical schema name for a scraped series (identity for unknowns)."""
    return SERIES_ALIASES.get(name, name)


# strtoll-equivalent integer token: optional sign, digits, space/tab padding
# (mirrors the native kernel's parse_full_int so both parsers accept/reject
# identical accelerator_id suffixes — incl. rejecting "1_5", "0x3", "").
_INT_RE = re.compile(r"^[ \t]*[+-]?[0-9]+[ \t]*$")
_I64_MIN, _I64_MAX = -(2**63), 2**63 - 1


def _strict_int(s: str) -> "int | None":
    if not _INT_RE.match(s):
        return None
    v = int(s)
    if not (_I64_MIN <= v <= _I64_MAX):  # strtoll ERANGE → skip series
        return None
    return v


def split_accelerator_id(value: str) -> "tuple[str, int] | None":
    """``"<board-id>-<chip-index>"`` → (board prefix, chip index).

    GKE accelerator ids put the per-node chip index after the final ``-``;
    the prefix identifies the board/node.  A bare integer (no ``-``) maps to
    ("", chip).  Returns None when no integer chip index can be extracted.
    """
    pos = value.rfind("-")
    if pos < 0:
        chip = _strict_int(value)
        return ("", chip) if chip is not None else None
    chip = _strict_int(value[pos + 1 :])
    if chip is None:
        return None
    return (value[:pos], chip)


def resolve_identity(labels, default_slice: str):
    """Shared label rules: labels mapping → (slice, host, chip_id, accel),
    or None when the series carries no parseable chip identity.

    Fallback chains (canonical label first, reference-exporter analogues and
    GKE device-plugin labels after):

    - chip:  ``chip_id`` → ``gpu_id`` → ``accelerator_id`` suffix
    - slice: ``slice`` → ``accelerator_id`` board prefix → default
    - host:  ``host`` → ``node`` → ``instance``
    - accel: ``accelerator`` → ``card_model`` → ``model``

    The native kernel implements the identical rules in C++
    (frame_kernel.cc emit paths); change both together.
    """
    chip_label = labels.get("chip_id")
    if chip_label is None:
        chip_label = labels.get("gpu_id")
    slice_hint = None
    if chip_label is not None:
        if isinstance(chip_label, bool):
            # JSON true/false: the native parser sees the literal text,
            # which never parses as an integer — skip on both sides
            return None
        if isinstance(chip_label, int):
            chip_id = chip_label
        elif isinstance(chip_label, str):
            # strict [ \t]-bounded parse mirroring the native strtoll
            # wrapper — a bare int() accepts exotic whitespace ("\x0c5")
            # and underscores the native side rejects
            parsed_id = _strict_int(chip_label)
            if parsed_id is None:
                return None
            chip_id = parsed_id
        else:
            return None
    else:
        accel_id = labels.get("accelerator_id")
        if not isinstance(accel_id, str):
            # JSON integer label values keep their exact text form in the
            # native parser; mirror that (floats/bools never round-trip
            # identically, so both parsers skip them)
            if isinstance(accel_id, bool) or not isinstance(accel_id, int):
                return None
            accel_id = str(accel_id)
        parsed = split_accelerator_id(accel_id)
        if parsed is None:
            return None
        prefix, chip_id = parsed
        if prefix:
            slice_hint = prefix
    slice_id = labels.get("slice")
    if slice_id is None:
        slice_id = slice_hint if slice_hint is not None else default_slice
    host = labels.get("host")
    if host is None:
        host = labels.get("node")
        if host is None:
            host = labels.get("instance", "")
    accel = labels.get("accelerator")
    if accel is None:
        accel = labels.get("card_model")
        if accel is None:
            accel = labels.get("model", "")
    return slice_id, host, chip_id, accel


def native_alias_table() -> str:
    """C++ source for the generated ``series_aliases.inc`` header the native
    kernel compiles in — keeps the C++ alias table in lock-step with
    SERIES_ALIASES (tpudash/native rebuilds when this content changes)."""
    lines = [
        "// Generated by tpudash.compat.native_alias_table() — do not edit.",
        "static const struct { const char* from; const char* to; }",
        "    kSeriesAliases[] = {",
    ]
    for src, dst in sorted(SERIES_ALIASES.items()):
        lines.append(f'    {{"{src}", "{dst}"}},')
    lines.append("};")
    return "\n".join(lines) + "\n"
