"""Follower (hot-standby) mode: a read-only TSDB that tails another
instance's segment directory.

The leader's segment files are append-only CRC-framed records (store.py
``_write_record``), which makes replication a byte-offset tail: the
follower remembers, per file, how many bytes it has applied, and each
:meth:`poll` reads whatever grew past that offset, parses the COMPLETE
frames in it, and commits the blocks/rollups into its own in-memory
tiers.  A frame whose declared length outruns the bytes on disk is the
leader mid-write — the follower simply stops before it and picks the
frame up whole on the next poll.  Nothing the follower does ever mutates
the leader's files (``read_only`` prevents truncation and reclaim), so
it is safe to point at a LIVE leader — or at a snapshot directory, which
is just a smaller segment set with a manifest it ignores.

Leader-side retention is survivable by construction: when the leader
reclaims an expired segment the follower merely drops its tail cursor
for the vanished file — every record it already applied stays queryable
until the follower's OWN retention expires it.  A segment reclaimed
before the follower ever tailed it is history the leader no longer
serves either; the follower converges on the leader's remaining horizon
(the killall drill asserts exactly this).

Replication lag is measured, not guessed: ``lag_s`` is the age of the
newest record at the moment it was applied (write→apply delay ≈ the
leader's seal cadence + one poll interval) and ``caught_up`` says every
known file was consumed to its end on the last poll.  Both surface via
:meth:`stats` → ``/api/timings`` (``tier.replication_lag_s``) — the
number federation's hot-standby reads will alert on.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib

from tpudash.tsdb.store import (
    _FRAME_HDR,
    _MAGIC,
    _REC_BLOCK,
    _REC_ROLLUP,
    _REC_SKETCH,
    TSDB,
    _parse_block,
    _parse_rollup,
    _parse_sketch,
)

log = logging.getLogger(__name__)

_TIERS = ("raw", "1m", "10m")


class FollowerTSDB(TSDB):
    """Read-only standby over ``follow_path``; every query surface of
    :class:`TSDB` (range_query, series listings, stats) works unchanged.
    ``append_frame`` is inert — a follower never originates data."""

    def __init__(
        self,
        follow_path: str,
        poll_interval_s: float = 2.0,
        retention_raw_s: float = 86400.0,
        retention_1m_s: float = 7 * 86400.0,
        retention_10m_s: float = 30 * 86400.0,
    ) -> None:
        super().__init__(
            path="",  # no segments of its own — in-memory tiers only
            retention_raw_s=retention_raw_s,
            retention_1m_s=retention_1m_s,
            retention_10m_s=retention_10m_s,
            read_only=True,
        )
        self.follow_path = follow_path
        self.poll_interval_s = max(0.05, float(poll_interval_s))
        #: file name → [applied_offset, stuck_reason|None]
        self._tails: "dict[str, list]" = {}
        #: newest RAW sample stamp applied (rollup t1s are bucket-aligned
        #: ends that can postdate real samples — useless for lag/age)
        self._newest_raw_ms = 0
        #: one poll at a time (the background thread and ad-hoc callers)
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.replication = {
            "leader": follow_path,
            "connected": False,
            "caught_up": False,
            "lag_s": None,
            "last_poll_ts": None,
            "files_tailed": 0,
            "files_reclaimed": 0,
            "records_applied": 0,
            "stuck_files": [],
            "last_error": None,
        }
        self.poll()  # initial catch-up before anyone queries

    @classmethod
    def from_config(cls, cfg) -> "FollowerTSDB":
        return cls(
            cfg.tsdb_follow,
            poll_interval_s=cfg.tsdb_follow_interval,
            retention_raw_s=cfg.tsdb_retention_raw,
            retention_1m_s=cfg.tsdb_retention_1m,
            retention_10m_s=cfg.tsdb_retention_10m,
        )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Begin tailing on a daemon thread at ``poll_interval_s``."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tsdb-follower", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — the tail loop must survive one bad poll  # tpulint: allow[broad-except] replication heartbeat: one failed poll logs, the next retries
                log.warning("tsdb follower poll failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._closed = True

    # -- replication ---------------------------------------------------------
    def poll(self) -> dict:
        """One tail pass over the leader directory.  Returns (and stores
        on ``replication``) the pass summary."""
        with self._poll_lock:  # tpulint: allow[blocking-under-lock] dedicated tail-poll lock: serializes pollers only; queries ride _lock, never this
            return self._poll_locked()

    def _poll_locked(self) -> dict:
        rep = dict(self.replication)
        rep["last_poll_ts"] = time.time()  # tpulint: allow[wall-clock] replication lag compares persisted epoch stamps
        applied = 0
        newest_applied = 0
        try:
            names = sorted(os.listdir(self.follow_path))
        except OSError as e:
            rep["connected"] = False
            rep["caught_up"] = False
            rep["last_error"] = str(e)
            self.replication = rep
            return rep
        rep["connected"] = True
        rep["last_error"] = None
        seg_names = [
            n
            for n in names
            if n.endswith(".seg") and n.split("-", 1)[0] in _TIERS
        ]
        #: files this pass could not read to their end — transient
        #: (reclaim race, EACCES): they make the pass NOT caught up
        skipped = 0
        for name in seg_names:
            tail = self._tails.setdefault(name, [0, None])
            if tail[1] is not None:
                continue  # poisoned file: corruption, not a torn tail
            full = os.path.join(self.follow_path, name)
            try:
                size = os.path.getsize(full)
                if size <= tail[0]:
                    continue
                with open(full, "rb") as f:  # tpulint: allow[blocking-under-lock] the poll lock IS the dedicated tail-I/O lock; queries ride _lock, never this
                    f.seek(tail[0])
                    data = f.read(size - tail[0])
            except OSError:
                skipped += 1
                continue  # raced a leader-side reclaim; next poll drops it
            consumed, records, newest, stuck = self._apply_frames(data)
            tail[0] += consumed
            tail[1] = stuck
            applied += records
            newest_applied = max(newest_applied, newest)
            self._newest_raw_ms = max(self._newest_raw_ms, newest)
            if stuck is not None:
                log.warning(
                    "tsdb follower: %s poisoned at offset %d (%s); "
                    "holding applied data, ignoring the rest of the file",
                    name, tail[0], stuck,
                )
        # leader-side reclaim: files gone from the directory lose their
        # cursor; everything already applied stays until OUR retention
        for name in list(self._tails):
            if name not in seg_names:
                del self._tails[name]
                rep["files_reclaimed"] += 1
        if applied:
            with self._lock:
                self.version += 1
            self._enforce_retention()
            if newest_applied:
                # write→apply delay of the newest record, measured at
                # apply time — THE replication-lag number
                rep["lag_s"] = round(
                    max(0.0, rep["last_poll_ts"] - newest_applied / 1000.0),
                    3,
                )
        rep["records_applied"] += applied
        rep["files_tailed"] = len(self._tails)
        rep["stuck_files"] = sorted(
            n for n, t in self._tails.items() if t[1] is not None
        )
        # caught up = this pass consumed every readable file to its end
        # AND nothing is poisoned or unreadable — a promotion decision
        # reads this, so "behind but quiet" must never report True
        # (incomplete trailing frames don't count: that's the leader
        # mid-write, fully consumed next poll)
        rep["caught_up"] = not rep["stuck_files"] and skipped == 0
        self.replication = rep
        return rep

    def _apply_frames(self, data: bytes):
        """Parse + commit every complete frame in ``data``.  Returns
        (bytes consumed, records applied, newest t1 applied,
        stuck_reason|None).  An incomplete trailing frame (leader
        mid-write) is simply not consumed; a frame that is fully present
        but fails magic/CRC is corruption — the file is poisoned rather
        than spun on."""
        off = 0
        records = 0
        newest = 0
        stuck = None
        while off + _FRAME_HDR.size <= len(data):
            try:
                magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(data, off)
            except struct.error:
                break
            end = off + _FRAME_HDR.size + plen
            if magic != _MAGIC:
                stuck = "bad frame magic"
                break
            if end > len(data):
                break  # incomplete: the leader is mid-write, retry later
            payload = data[off + _FRAME_HDR.size : end]
            if zlib.crc32(payload) != crc:
                stuck = "record CRC mismatch"
                break
            try:
                if rec_type == _REC_BLOCK:
                    b = _parse_block(payload)
                    with self._lock:
                        self._raw.append(b)
                    newest = max(newest, b.t1)
                    records += 1
                elif rec_type == _REC_ROLLUP:
                    r = _parse_rollup(payload)
                    if r.tier_ms in self._rollups:
                        with self._lock:
                            self._rollups[r.tier_ms].append(r)
                        # NOT folded into ``newest``: a rollup's t1 is its
                        # bucket-aligned end, which can postdate the newest
                        # real sample by up to a bucket — lag is measured
                        # against raw block stamps only
                        records += 1
                elif rec_type == _REC_SKETCH:
                    s = _parse_sketch(payload)
                    if s.tier_ms in self._sketches:
                        with self._lock:
                            self._sketches[s.tier_ms].append(s)
                        records += 1
                # unknown record types (newer leader): skip the framed
                # payload, keep tailing — version skew must not poison
                # the file
            except (ValueError, KeyError, struct.error) as e:
                stuck = f"unparseable payload: {e}"
                break
            off = end
        return off, records, newest, stuck

    def stats(self) -> dict:
        out = super().stats()
        rep = dict(self.replication)
        # data age complements lag: how old the newest standby sample is
        # right now (grows while the leader is idle; lag_s does not)
        rep["data_age_s"] = (
            round(max(0.0, time.time() - self._newest_raw_ms / 1000.0), 3)  # tpulint: allow[wall-clock] replication lag compares persisted epoch stamps
            if self._newest_raw_ms
            else None
        )
        out["replication"] = rep
        return out
