"""Gorilla compression for (timestamp, value) streams.

The tsdb's on-disk and in-memory chunk format: Facebook's Gorilla paper
(VLDB'15, the scheme Prometheus/M3/InfluxDB descend from) — timestamps
as delta-of-delta with tight bit buckets, values as XOR of IEEE-754
bits with leading/trailing-zero windows.  Monitoring streams are
near-periodic (delta-of-delta ≈ 0) and near-constant (XOR ≈ 0), so a
(ts, float64) pair that costs ~37 bytes as JSON typically lands between
2 and 20 **bits** here; the fixture corpus in tests/test_tsdb.py pins
the ratio at ≥ 5× vs the raw JSON history representation.

Contract:

- Timestamps are **integer milliseconds** (the store quantizes; one ms
  is far below the dashboard's refresh cadence).  Any int64 sequence
  round-trips exactly — including non-monotonic and negative deltas
  (clock steps, out-of-order appends): delta-of-delta is signed.
- Values are float64 **bit patterns**: NaN, ±inf, -0.0 and every other
  bit pattern round-trip exactly (NaN is how the store spells "series
  had no sample at this shared timestamp").
- Decoders take the point count (chunks carry it in their header);
  the streams themselves are not self-terminating.

Pure Python + stdlib on purpose: the codec must import everywhere the
dashboard does (no native build, no new deps).  Encode runs at a few
hundred ns–µs per point, far above the ingest rate of any realistic
fleet cadence; chunk sealing runs off the publish path regardless
(tpudash/tsdb/store.py).
"""

from __future__ import annotations

import struct

_U64 = 0xFFFFFFFFFFFFFFFF

# delta-of-delta bit buckets (prefix, payload bits) — Prometheus's
# spread, one 64-bit escape so any int64 sequence encodes
_DOD_BUCKETS = (
    (0b10, 2, 14),
    (0b110, 3, 17),
    (0b1110, 4, 20),
)


class _BitWriter:
    __slots__ = ("buf", "acc", "nbits")

    def __init__(self) -> None:
        self.buf = bytearray()
        self.acc = 0
        self.nbits = 0

    def write(self, value: int, bits: int) -> None:
        self.acc = (self.acc << bits) | (value & ((1 << bits) - 1))
        self.nbits += bits
        while self.nbits >= 8:
            self.nbits -= 8
            self.buf.append((self.acc >> self.nbits) & 0xFF)
        self.acc &= (1 << self.nbits) - 1

    def getvalue(self) -> bytes:
        if self.nbits:
            return bytes(self.buf) + bytes(
                [(self.acc << (8 - self.nbits)) & 0xFF]
            )
        return bytes(self.buf)


class _BitReader:
    __slots__ = ("data", "pos", "limit")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0  # bit offset
        self.limit = len(data) * 8

    def read(self, bits: int) -> int:
        out = 0
        pos = self.pos
        if pos + bits > self.limit:
            raise ValueError("gorilla stream truncated")
        data = self.data
        for _ in range(bits):
            byte = data[pos >> 3]
            out = (out << 1) | ((byte >> (7 - (pos & 7))) & 1)
            pos += 1
        self.pos = pos
        return out

    def read_bit(self) -> int:
        pos = self.pos
        if pos >= self.limit:
            raise ValueError("gorilla stream truncated")
        bit = (self.data[pos >> 3] >> (7 - (pos & 7))) & 1
        self.pos = pos + 1
        return bit


def _signed(value: int, bits: int) -> int:
    """Reinterpret a ``bits``-wide unsigned field as two's complement."""
    if value >= 1 << (bits - 1):
        value -= 1 << bits
    return value


def encode_timestamps(ts_ms) -> bytes:
    """Delta-of-delta encode integer-millisecond timestamps — through
    the native kernel when available (the tsdb seal hot loop), else the
    pure-Python encoder below.  Both emit identical bytes (differential
    fuzz in tests/test_tsdb.py); the Python codec remains the always-
    tested fallback and the only decoder."""
    from tpudash import native

    if native.is_available():
        return native.gorilla_encode_timestamps(ts_ms)
    return encode_timestamps_py(ts_ms)


def encode_values(values) -> bytes:
    """XOR-encode float64 values — native kernel when available, same
    byte-exact contract as :func:`encode_timestamps`."""
    from tpudash import native

    if native.is_available():
        return native.gorilla_encode_values(values)
    return encode_values_py(values)


def encode_timestamps_py(ts_ms) -> bytes:
    """Pure-Python delta-of-delta encode (reference implementation).

    All delta arithmetic is mod 2^64: a delta (or delta-of-delta)
    between two extreme int64 stamps needs 65 bits as a plain integer,
    so both sides wrap to the 64-bit ring and the decoder reinterprets
    — ANY int64 sequence round-trips, however violent the clock step."""
    w = _BitWriter()
    if not ts_ms:
        return b""
    prev = int(ts_ms[0])
    w.write(prev, 64)
    prev_delta = 0  # mod-2^64 representative
    for raw in ts_ms[1:]:
        t = int(raw)
        delta = (t - prev) & _U64
        dod = _signed((delta - prev_delta) & _U64, 64)
        prev, prev_delta = t, delta
        if dod == 0:
            w.write(0, 1)
            continue
        for prefix, plen, bits in _DOD_BUCKETS:
            if -(1 << (bits - 1)) <= dod < (1 << (bits - 1)):
                w.write(prefix, plen)
                w.write(dod, bits)
                break
        else:
            w.write(0b1111, 4)
            w.write(dod, 64)
    return w.getvalue()


def decode_timestamps(data: bytes, count: int) -> "list[int]":
    """Decode ``count`` delta-of-delta timestamps.  ``data`` and
    ``count`` come from untrusted chunk headers: a stream too short for
    its advertised count (truncation, or a count a tiny payload could
    never encode) raises :class:`ValueError` — never IndexError, and
    never count-proportional work the bytes don't back."""
    if count <= 0:
        return []
    # cheapest possible encoding is 64 bits for the first point plus
    # one bit per further point — an advertised count above that is a
    # length-field inflation, refused before any decode work
    if 64 + (count - 1) > len(data) * 8:
        raise ValueError("gorilla count exceeds stream capacity")
    r = _BitReader(data)
    first = _signed(r.read(64), 64)
    out = [first]
    prev, prev_delta = first, 0
    for _ in range(count - 1):
        if r.read_bit() == 0:
            dod = 0
        elif r.read_bit() == 0:
            dod = _signed(r.read(14), 14)
        elif r.read_bit() == 0:
            dod = _signed(r.read(17), 17)
        elif r.read_bit() == 0:
            dod = _signed(r.read(20), 20)
        else:
            dod = _signed(r.read(64), 64)
        # same mod-2^64 ring as the encoder; only the emitted timestamp
        # is folded back to signed int64
        prev_delta = (prev_delta + dod) & _U64
        prev = _signed((prev + prev_delta) & _U64, 64)
        out.append(prev)
    return out


def encode_values_py(values) -> bytes:
    """Pure-Python XOR encode (Gorilla §4.1.2).  Accepts any iterable
    of floats (numpy scalars included); bit patterns are preserved."""
    w = _BitWriter()
    pack = struct.pack
    unpack = struct.unpack
    prev_bits = None
    lead = trail = -1  # no reusable window yet
    for v in values:
        bits = unpack("<Q", pack("<d", float(v)))[0]
        if prev_bits is None:
            w.write(bits, 64)
            prev_bits = bits
            continue
        xor = bits ^ prev_bits
        prev_bits = bits
        if xor == 0:
            w.write(0, 1)
            continue
        cur_lead = 64 - xor.bit_length()
        if cur_lead > 31:
            cur_lead = 31  # 5-bit field; deeper zeros ride the payload
        cur_trail = (xor & -xor).bit_length() - 1
        if (
            lead >= 0
            and cur_lead >= lead
            and cur_trail >= trail
        ):
            # fits the previous window: control '10' + meaningful bits
            w.write(0b10, 2)
            w.write(xor >> trail, 64 - lead - trail)
        else:
            # new window: '11' + 5b leading + 6b significant-bit count
            # (64 wraps to 0 in the 6-bit field, decoded back as 64)
            lead, trail = cur_lead, cur_trail
            sig = 64 - lead - trail
            w.write(0b11, 2)
            w.write(lead, 5)
            w.write(sig & 0x3F, 6)
            w.write(xor >> trail, sig)
    return w.getvalue()


def decode_values(data: bytes, count: int) -> "list[float]":
    """Decode ``count`` XOR-encoded float64 values; same untrusted-input
    contract as :func:`decode_timestamps` (ValueError on truncated or
    count-inflated streams)."""
    if count <= 0:
        return []
    if 64 + (count - 1) > len(data) * 8:
        raise ValueError("gorilla count exceeds stream capacity")
    r = _BitReader(data)
    pack = struct.pack
    unpack = struct.unpack
    bits = r.read(64)
    out = [unpack("<d", pack("<Q", bits))[0]]
    lead = trail = 0
    for _ in range(count - 1):
        if r.read_bit() == 0:
            pass  # identical bits
        else:
            if r.read_bit():  # new window
                lead = r.read(5)
                sig = r.read(6)
                if sig == 0:
                    sig = 64
                trail = 64 - lead - sig
            sig = 64 - lead - trail
            bits ^= r.read(sig) << trail
        out.append(unpack("<d", pack("<Q", bits & _U64))[0])
    return out


def ts_to_ms(ts_s: float) -> int:
    """Epoch seconds (float) → the store's integer-millisecond domain."""
    return int(round(ts_s * 1000.0))


def ms_to_ts(ts_ms: int) -> float:
    return ts_ms / 1000.0
