"""Pluggable object store for the cold tier: put/get/list/delete over
opaque keys, with the filesystem backend first.

Why an interface at all: the cold tier's crash-safety protocol
(stage → upload → digest read-back → only then retire local segments,
tpudash/tsdb/compact.py) is the hard part; the transport is not.  The
:class:`ObjectStore` surface is the minimal contract that protocol
needs — atomicity is deliberately NOT part of it (real object stores
tear, time out, and go dark), which is why every consumer verifies
what it reads instead of trusting what it wrote.

The :class:`FilesystemStore` backend keeps the dependency-free
constraint (a directory is the bucket) and carries **injectable fault
hooks** (:class:`FaultPlan`) so the chaos drills can produce the
failures a real store produces: torn uploads (a non-atomic backend
dying mid-PUT), transient errors, and a fully dark endpoint.  An S3/GCS
backend registers its scheme via :func:`register_backend` without this
module growing an SDK import.

Every backend error surfaces as :class:`ObjectStoreError` — callers
handle exactly one exception type, and nothing here ever raises into a
query path (the cold tier catches, degrades, and marks itself
unreachable; see tpudash/tsdb/cold.py).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

#: staged-upload prefix FilesystemStore writes through; a crash mid-put
#: leaves one of these — listings never surface them (ignorable husks)
_TMP_PREFIX = ".put-"


class ObjectStoreError(Exception):
    """A store operation failed (transport, backend, or injected fault).
    The cold tier treats every instance the same way: retry under the
    deadline, then degrade — never crash, never serve a guess."""


class FaultPlan:
    """Injectable fault hooks for chaos drills and tests.  Mutated by
    the test/drill thread, read by store operations; plain attribute
    writes are atomic enough for the drills' purposes."""

    def __init__(self) -> None:
        #: every operation raises (endpoint unreachable / auth dead)
        self.dark = False
        #: next N puts raise AFTER writing a torn prefix to the final
        #: key — the non-atomic-backend crash a digest read-back catches
        self.torn_puts = 0
        #: next N puts raise without writing anything (transient 5xx)
        self.fail_puts = 0
        #: next N gets raise (transient read failure)
        self.fail_gets = 0
        #: per-operation added latency, seconds (slows a drill's window
        #: so kill -9 lands mid-transfer)
        self.latency_s = 0.0
        # observed counters (drill summaries)
        self.puts_torn = 0
        self.puts_failed = 0
        self.gets_failed = 0

    def _gate(self, op: str) -> None:
        if self.latency_s > 0:
            time.sleep(self.latency_s)
        if self.dark:
            raise ObjectStoreError(f"injected fault: store dark ({op})")


class ObjectStore:
    """Abstract key→bytes store.  Keys are ``/``-separated relative
    paths (``bundles/bundle-....tdb``); values are immutable once
    written (overwrite = replace whole object)."""

    scheme = ""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str, start: int = 0, length: "int | None" = None) -> bytes:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def list(self, prefix: str = "") -> "list[str]":
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover — backends with handles
        return

    def describe(self) -> str:
        return f"{self.scheme}://"


def _check_key(key: str) -> str:
    """Refuse absolute/escaping keys before they touch a filesystem."""
    if not key or key.startswith(("/", "\\")) or ".." in key.split("/"):
        raise ObjectStoreError(f"invalid object key {key!r}")
    return key


class FilesystemStore(ObjectStore):
    """A directory as the bucket.  Writes go through a same-directory
    temp file + ``os.replace`` so an OS-level crash cannot tear a PUT —
    but consumers must NOT rely on that: the :class:`FaultPlan` torn-put
    hook (and any real remote backend) produces exactly the partial
    object the digest read-back protocol exists to catch."""

    scheme = "file"

    def __init__(self, root: str, faults: "FaultPlan | None" = None) -> None:
        self.root = root
        self.faults = faults or FaultPlan()
        # create the bucket up front: a fresh spec must list as EMPTY,
        # not unreachable.  A root that later VANISHES (unmounted
        # volume) still errors — that distinction is the dark-store
        # signal, so no exist_ok-style suppression beyond this point.
        with contextlib.suppress(OSError):
            os.makedirs(root, exist_ok=True)
        #: serializes multi-writer puts to one key (compactor re-upload
        #: racing a verify read is resolved by the digest check, not here)
        self._put_lock = threading.Lock()

    def _full(self, key: str) -> str:
        return os.path.join(self.root, _check_key(key))

    def put(self, key: str, data: bytes) -> None:
        f = self.faults
        f._gate("put")
        if f.fail_puts > 0:
            f.fail_puts -= 1
            f.puts_failed += 1
            raise ObjectStoreError("injected fault: put failed")
        full = self._full(key)
        try:
            with self._put_lock:  # tpulint: allow[blocking-under-lock] dedicated object-PUT lock: serializes writers only; reads never take it
                os.makedirs(os.path.dirname(full) or self.root, exist_ok=True)
                if f.torn_puts > 0:
                    f.torn_puts -= 1
                    f.puts_torn += 1
                    # the non-atomic backend dying mid-transfer: half the
                    # bytes land on the FINAL key, then the "connection"
                    # drops — read-back verification must catch this
                    with open(full, "wb") as out:
                        out.write(data[: max(1, len(data) // 2)])
                        out.flush()
                        os.fsync(out.fileno())
                    raise ObjectStoreError("injected fault: torn put")
                tmp = os.path.join(
                    os.path.dirname(full),
                    f"{_TMP_PREFIX}{os.path.basename(full)}.{os.getpid()}",
                )
                with open(tmp, "wb") as out:
                    out.write(data)
                    out.flush()
                    os.fsync(out.fileno())
                os.replace(tmp, full)
        except OSError as e:
            raise ObjectStoreError(f"put {key}: {e}") from e

    def get(self, key: str, start: int = 0, length: "int | None" = None) -> bytes:
        f = self.faults
        f._gate("get")
        if f.fail_gets > 0:
            f.fail_gets -= 1
            f.gets_failed += 1
            raise ObjectStoreError("injected fault: get failed")
        try:
            with open(self._full(key), "rb") as fin:
                if start:
                    fin.seek(start)
                return fin.read() if length is None else fin.read(length)
        except OSError as e:
            raise ObjectStoreError(f"get {key}: {e}") from e

    def size(self, key: str) -> int:
        self.faults._gate("size")
        try:
            return os.path.getsize(self._full(key))
        except OSError as e:
            raise ObjectStoreError(f"size {key}: {e}") from e

    def list(self, prefix: str = "") -> "list[str]":
        self.faults._gate("list")
        try:
            if not os.path.isdir(self.root):
                raise ObjectStoreError(f"list: store root {self.root} missing")
            out: "list[str]" = []
            for dirpath, _dirs, names in os.walk(self.root):
                rel = os.path.relpath(dirpath, self.root)
                rel = "" if rel == "." else rel.replace(os.sep, "/") + "/"
                for n in names:
                    if n.startswith(_TMP_PREFIX):
                        continue  # crash husk from a torn local put
                    key = rel + n
                    if key.startswith(prefix):
                        out.append(key)
            return sorted(out)
        except OSError as e:
            raise ObjectStoreError(f"list {prefix!r}: {e}") from e

    def delete(self, key: str) -> None:
        self.faults._gate("delete")
        with contextlib.suppress(OSError):
            os.remove(self._full(key))

    def describe(self) -> str:
        return f"file://{self.root}"


#: scheme → factory(rest_of_spec) registry; the filesystem backend is
#: built in, remote backends register here at import time
_BACKENDS: "dict[str, object]" = {}


def register_backend(scheme: str, factory) -> None:
    """Make ``scheme://...`` specs resolvable by :func:`open_store` —
    the plug point for an S3/GCS backend living outside this module."""
    _BACKENDS[scheme] = factory


def open_store(spec: str) -> ObjectStore:
    """Resolve a ``TPUDASH_COLD_STORE`` spec to a backend: a bare path
    or ``file:///path`` opens a :class:`FilesystemStore`; other schemes
    go through :func:`register_backend`.  Raises ``ValueError`` on an
    unknown scheme — a typo'd spec must fail at startup, not at the
    first upload."""
    if not spec:
        raise ValueError("empty object-store spec")
    if "://" in spec:
        scheme, rest = spec.split("://", 1)
        if scheme == "file":
            return FilesystemStore(rest or "/")
        factory = _BACKENDS.get(scheme)
        if factory is None:
            raise ValueError(
                f"unknown object-store scheme {scheme!r} "
                "(built-in: file://; others via register_backend)"
            )
        return factory(rest)
    return FilesystemStore(spec)
