"""Tiered downsampling for the tsdb: raw → 1m → 10m.

A rollup block is the aggregate shadow of one sealed raw block: per
time bucket (60 s / 600 s), per series, the (min, max, sum, count)
quadruple — everything ``mean`` needs without keeping the points.
Rollups are written at seal time alongside the raw block and carry
their own (longer) retention, so ``/api/range`` keeps answering with
min/max/mean long after the raw points expired (the whole reason a
live gauge page becomes a diagnosis tool — PAPERS.md fleet-telemetry
thread).

Bucket edges are epoch-aligned (``ts // tier_ms``), so two blocks that
split one wall-clock bucket between them each contribute a *partial*
quadruple; merging partials is exact for min/max/sum/count (and hence
mean) — the query layer folds them (``merge_quads``).  Nothing here is
approximate: a rollup bucket's mean equals the mean of the raw points
it covered, NaN cells excluded.

Arrays are float32/int32 (the raw matrices are float32 already): one
bucket costs 16 bytes per series per tier, ~160 KB per 10 minutes at
256 chips × 10 metrics — and the 10m tier is 10× smaller again.
"""

from __future__ import annotations

import numpy as np

#: tier bucket widths, ms
TIER_1M_MS = 60_000
TIER_10M_MS = 600_000
TIERS_MS = (TIER_1M_MS, TIER_10M_MS)

#: pseudo series key for the CROSS-SERIES (fleet-distribution) sketch:
#: every real chip's samples of one bucket folded into one digest.
#: ``__``-prefixed like the other pseudo keys, so it can never collide
#: with a real ``slice/chip`` key — and ``__``-prefixed series (the
#: fleet-average row, recording-rule outputs) are excluded FROM it,
#: or derived series would double-count the chips they summarize.
ALL_KEY = "__all__"


class RollupBlock:
    """Aggregates of one raw block for one tier: ``buckets`` (int64
    epoch-ms bucket starts, ascending) × ``keys`` × ``cols`` arrays of
    min/max/sum/count.  Immutable once built."""

    __slots__ = ("tier_ms", "buckets", "keys", "cols", "mn", "mx", "sm",
                 "cnt", "src_t0", "src_t1")

    def __init__(self, tier_ms, buckets, keys, cols, mn, mx, sm, cnt,
                 src_t0, src_t1):
        self.tier_ms = int(tier_ms)
        self.buckets = buckets
        self.keys = list(keys)
        self.cols = list(cols)
        self.mn = mn
        self.mx = mx
        self.sm = sm
        self.cnt = cnt
        #: raw-time bounds of the points that fed this block — window
        #: filtering and "how far back does this tier reach" use these,
        #: never the bucket edges (a bucket EDGE can sit well outside
        #: the data that landed in it)
        self.src_t0 = int(src_t0)
        self.src_t1 = int(src_t1)

    @property
    def t0(self) -> int:
        return int(self.buckets[0]) if len(self.buckets) else 0

    @property
    def t1(self) -> int:
        """Last covered instant: the end of the final bucket (retention
        uses this — conservative, keeps a bucket until it fully ages)."""
        if not len(self.buckets):
            return 0
        return int(self.buckets[-1]) + self.tier_ms - 1

    def series_quads(self, key: str, col: str):
        """[(bucket_ms, mn, mx, sm, cnt)] for one series; [] when the
        block does not carry it (series churn: the chip was absent)."""
        try:
            ki = self.keys.index(key)
            ci = self.cols.index(col)
        except ValueError:
            return []
        out = []
        for b in range(len(self.buckets)):
            c = int(self.cnt[b, ki, ci])
            if c <= 0:
                continue
            out.append(
                (
                    int(self.buckets[b]),
                    float(self.mn[b, ki, ci]),
                    float(self.mx[b, ki, ci]),
                    float(self.sm[b, ki, ci]),
                    c,
                )
            )
        return out


def rollup_points(tier_ms, ts_ms, keys, cols, stacked) -> "RollupBlock | None":
    """Aggregate a (n, K, C) float matrix stack at timestamps ``ts_ms``
    into one RollupBlock.  NaN cells contribute nothing (count stays
    honest); an all-NaN bucket keeps count 0 and is skipped at query
    time.  Vectorized: one fmin/fmax/nansum pass per bucket."""
    n = len(ts_ms)
    if n == 0:
        return None
    ts = np.asarray(ts_ms, dtype=np.int64)
    bucket_ids = ts // tier_ms
    uniq = np.unique(bucket_ids)
    K, C = stacked.shape[1], stacked.shape[2]
    mn = np.full((len(uniq), K, C), np.nan, dtype=np.float32)
    mx = np.full((len(uniq), K, C), np.nan, dtype=np.float32)
    sm = np.zeros((len(uniq), K, C), dtype=np.float64)
    cnt = np.zeros((len(uniq), K, C), dtype=np.int32)
    for i, b in enumerate(uniq):
        rows = stacked[bucket_ids == b]
        with np.errstate(invalid="ignore"):  # ±inf cells: inf-inf is NaN, fine
            mn[i] = np.fmin.reduce(rows, axis=0)
            mx[i] = np.fmax.reduce(rows, axis=0)
            sm[i] = np.nansum(rows, axis=0, dtype=np.float64)
        cnt[i] = np.sum(~np.isnan(rows), axis=0, dtype=np.int32)
    return RollupBlock(
        tier_ms,
        (uniq * tier_ms).astype(np.int64),
        keys,
        cols,
        mn,
        mx,
        sm.astype(np.float64),
        cnt,
        int(ts.min()),
        int(ts.max()),
    )


class SketchBlock:
    """Quantile-sketch shadow of one sealed raw block for one tier: per
    ``buckets[b]`` × ``keys[k]`` × ``cols[c]`` a serialized
    :class:`tpudash.analytics.sketch.QuantileSketch` (or None when the
    bucket carried no finite sample for that series).  ``keys`` always
    ends with :data:`ALL_KEY` — the fleet-distribution digest — and
    carries the real per-series keys only on tiers configured for them
    (``TPUDASH_SKETCH_SERIES``).  Immutable once built; digests stay
    serialized until a query touches them (a block's worth of parsed
    sketches would cost far more memory than the bytes do)."""

    __slots__ = ("tier_ms", "buckets", "keys", "cols", "enc",
                 "src_t0", "src_t1", "_key_pos")

    def __init__(self, tier_ms, buckets, keys, cols, enc, src_t0, src_t1):
        self.tier_ms = int(tier_ms)
        self.buckets = buckets
        self.keys = list(keys)
        self.cols = list(cols)
        #: enc[b][k][c] -> bytes | None
        self.enc = enc
        self.src_t0 = int(src_t0)
        self.src_t1 = int(src_t1)
        self._key_pos = None

    @property
    def t1(self) -> int:
        if not len(self.buckets):
            return 0
        return int(self.buckets[-1]) + self.tier_ms - 1

    def nbytes(self) -> int:
        return sum(
            len(e) for row in self.enc for cells in row for e in cells if e
        )

    def series(self, key: str, col: str):
        """[(bucket_ms, serialized_digest)] for one series; [] when the
        block does not carry it (per-series sketches off for this tier,
        or series churn)."""
        if self._key_pos is None:
            self._key_pos = {k: i for i, k in enumerate(self.keys)}
        ki = self._key_pos.get(key)
        if ki is None or col not in self.cols:
            return []
        ci = self.cols.index(col)
        out = []
        for b in range(len(self.buckets)):
            raw = self.enc[b][ki][ci]
            if raw:
                out.append((int(self.buckets[b]), raw))
        return out


def sketch_points(
    tier_ms, ts_ms, keys, cols, stacked, budget: int,
    per_series: bool,
) -> "SketchBlock | None":
    """Digest a (n, K, C) float stack into one SketchBlock: per bucket
    per column the fleet-distribution digest (:data:`ALL_KEY`, real
    chips only) plus — when ``per_series`` — each series' own temporal
    digest.  NaN cells contribute nothing, mirroring the quads."""
    n = len(ts_ms)
    if n == 0 or budget <= 0:
        return None
    ts = np.asarray(ts_ms, dtype=np.int64)
    bucket_ids = ts // tier_ms
    uniq = np.unique(bucket_ids)
    K, C = stacked.shape[1], stacked.shape[2]
    real = [k for k in range(K) if not str(keys[k]).startswith("__")]
    out_keys = (list(keys) if per_series else []) + [ALL_KEY]
    enc: list = []
    for b in uniq:
        rows = stacked[bucket_ids == b]  # (nb, K, C)
        per_bucket: list = []
        if per_series:
            for k in range(K):
                per_bucket.append([
                    _enc_or_none(rows[:, k, c], budget) for c in range(C)
                ])
        if real:
            per_bucket.append([
                _enc_or_none(rows[:, real, c], budget) for c in range(C)
            ])
        else:
            per_bucket.append([None] * C)
        enc.append(per_bucket)
    if not per_series and not real:
        return None  # nothing but pseudo series: no digest to keep
    return SketchBlock(
        tier_ms,
        (uniq * tier_ms).astype(np.int64),
        out_keys,
        cols,
        enc,
        int(ts.min()),
        int(ts.max()),
    )


def _enc_or_none(values, budget: int) -> "bytes | None":
    from tpudash.analytics.sketch import QuantileSketch

    sk = QuantileSketch.from_values(values, budget)
    return sk.to_bytes() if sk.count > 0 else None


def merge_quads(quads) -> "list[tuple]":
    """Merge per-block partial quadruples for ONE series into whole
    buckets: [(bucket_ms, mn, mx, sm, cnt)] sorted by bucket.  Exact —
    min of mins, max of maxes, sum of sums, sum of counts."""
    merged: dict = {}
    for b, mn, mx, sm, cnt in quads:
        cur = merged.get(b)
        if cur is None:
            merged[b] = [mn, mx, sm, cnt]
        else:
            if mn < cur[0]:
                cur[0] = mn
            if mx > cur[1]:
                cur[1] = mx
            cur[2] += sm
            cur[3] += cnt
    return [
        (b, q[0], q[1], q[2], q[3]) for b, q in sorted(merged.items())
    ]
