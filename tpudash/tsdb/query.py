"""Range queries over the tsdb: series select, step alignment,
aggregate choice, point budget.

The one read surface everything shares — the sparkline builder
(service._trends), the drill-down chip trends, ``GET /api/range``, and
the analytics plane's mergeable state builder
(tpudash/analytics/executor.py) all resolve their window through
:func:`resolve_window`, so resolution selection and step alignment have
exactly one implementation to test.

Resolution selection: the finest tier still *covering the window's
start* wins — raw first, then 1m, then 10m — except that a step wide
enough for a rollup tier (≥ its bucket width) prefers the rollup: the
answer is identical (rollups are exact min/max/sum/count) and the read
decodes 60–600× fewer points.  When nothing covers the start (asked
for more history than exists), the tier reaching furthest back serves
what it has — a shorter graph, never an error.

Quantile aggregates (``p50``/``p95``/``p99``) answer from the sealed
quantile sketches (tpudash/analytics/sketch.py) via
``store.sketch_series_window`` — per-bucket digests merged per step,
never a raw-tier decode on sketch-covered windows — at 1m resolution or
coarser (a digest cannot be split finer than its bucket).  A query with
no ``chip`` is the FLEET DISTRIBUTION: every real chip's samples,
which is what "fleet p99 duty cycle" means.

Step grids on rollup tiers are EPOCH-anchored (``bt // step * step``)
and the first emitted bucket clamps its timestamp into the request
window: an unaligned ``start`` must neither emit a bucket stamped
before ``start`` nor silently fold a whole out-of-window rollup bucket
into the first in-window one (the PR-13 alignment fix, regression-
pinned in tests/test_analytics.py).

The point budget is a hard ceiling: a query whose natural resolution
would return more than ``max_points`` is step-widened until it fits,
so one ``/api/range`` call can never ship (or force the server to
decode) an unbounded payload.
"""

from __future__ import annotations

from tpudash.tsdb import gorilla
from tpudash.tsdb.rollup import ALL_KEY, TIER_1M_MS, TIER_10M_MS

#: ``agg=`` values ``/api/range`` accepts
AGGREGATES = ("mean", "min", "max", "p50", "p95", "p99")

#: quantile aggregates → rank; answered from sketches, not quads
QUANTILE_AGGS = {"p50": 0.50, "p95": 0.95, "p99": 0.99}

#: default / ceiling for one query's returned points per column
DEFAULT_POINTS = 500
MAX_POINTS = 5000

_TIER_NAME = {0: "raw", TIER_1M_MS: "1m", TIER_10M_MS: "10m"}


def _choose_tier(store, start_ms: int, step_ms: int) -> int:
    """The tier this query reads (0 = raw).  Reach-back is judged on
    each tier's *source*-time earliest sample; a tier that merely
    reaches as far back as raw never beats raw (ties prefer finer)."""
    earliest = {t: store.earliest_ms(t) for t in (0, TIER_1M_MS, TIER_10M_MS)}
    e_raw = earliest[0]
    # a step at least one bucket wide prefers the exact-but-cheaper
    # rollup read — provided the rollup reaches back as far as raw does
    for tier in (TIER_10M_MS, TIER_1M_MS):
        e = earliest[tier]
        if (
            step_ms >= tier
            and e is not None
            and (e_raw is None or e <= max(start_ms, e_raw))
        ):
            return tier
    if e_raw is not None and e_raw <= start_ms:
        return 0
    # raw doesn't cover the start (expired, or asked before history
    # began): the tier reaching furthest back wins; ties prefer finer
    candidates = [(e, t) for t, e in earliest.items() if e is not None]
    if not candidates:
        return 0
    return min(candidates)[1]


def resolve_window(
    store,
    start_s: "float | None",
    end_s: "float | None",
    step_s: "float | None",
    max_points: int,
    agg: str = "mean",
) -> dict:
    """Resolve one query's effective window, step, and tier — shared by
    :func:`range_query` and the analytics state builder so the two
    paths can never disagree about alignment.  Returns ``{"start_ms",
    "end_ms", "step_ms", "tier", "resolution", "empty"}``; raises
    ValueError on a bad window."""
    if agg not in AGGREGATES:
        raise ValueError(f"agg must be one of {AGGREGATES}, not {agg!r}")
    max_points = max(1, min(int(max_points), MAX_POINTS))
    latest = store.latest_ms()
    end_ms = gorilla.ts_to_ms(end_s) if end_s is not None else latest
    if end_ms is None:
        return {
            "start_ms": int((start_s or 0.0) * 1000),
            "end_ms": int((end_s or 0.0) * 1000),
            "step_ms": int((step_s or 0.0) * 1000),
            "tier": 0,
            "resolution": "raw",
            "empty": True,
        }
    start_ms = (
        gorilla.ts_to_ms(start_s)
        if start_s is not None
        else end_ms - 3_600_000
    )
    if end_ms < start_ms:
        raise ValueError("end precedes start")
    window = max(1, end_ms - start_ms)
    step_ms = int(step_s * 1000) if step_s else 0
    if step_ms < 0:
        raise ValueError("step must be positive")
    # the budget is a ceiling, whatever step the caller asked for.
    # Epoch-anchored grids can emit one extra boundary bucket (a
    # partial at each window edge), so the divisor is max_points − 1
    min_step = -(-window // max(1, max_points - 1))  # ceil
    if step_ms and step_ms < min_step:
        step_ms = min_step
    if agg in QUANTILE_AGGS:
        # a digest cannot be split finer than its bucket: quantile
        # queries are 1m-resolution at finest, whatever the step asked
        step_ms = max(step_ms or 0, TIER_1M_MS, min_step)
    tier = _choose_tier(store, start_ms, step_ms)
    if tier != 0:
        if step_ms < tier:
            step_ms = tier  # a rollup can't answer finer than its bucket
        if step_ms < min_step:
            # the budget is a ceiling on EVERY tier: a 30-day stepless
            # query must not ship window/tier (~4300) bucket points just
            # because the rollup resolution happens to be fine
            step_ms = min_step
    return {
        "start_ms": start_ms,
        "end_ms": end_ms,
        "step_ms": step_ms,
        "tier": tier,
        "resolution": _TIER_NAME[tier],
        "empty": False,
    }


def _aggregate_raw(points, start_ms, end_ms, step_ms, agg):
    """Step-align raw (ts, value) points; NaN samples are skipped.
    Same EPOCH-anchored grid as the rollup and sketch paths (and the
    analytics state executor), so a child answering directly and a
    parent merging that child's state can never disagree about bucket
    timestamps.  Raw points are window-filtered individually, so only
    the first bucket's STAMP needs the clamp."""
    if step_ms <= 0:
        return [
            (gorilla.ms_to_ts(t), v) for t, v in points if v == v
        ]
    buckets: dict = {}
    for t, v in points:
        if v != v:
            continue
        b = t // step_ms * step_ms
        cur = buckets.get(b)
        if cur is None:
            buckets[b] = [v, v, v, 1]
        else:
            if v < cur[0]:
                cur[0] = v
            if v > cur[1]:
                cur[1] = v
            cur[2] += v
            cur[3] += 1
    return _emit(buckets, agg, start_ms)


def _aggregate_quads(quads, start_ms, step_ms, agg):
    """Step-align rollup quads — exact: min of mins, max of maxes,
    sum/count for the mean — on an EPOCH-anchored grid, each source
    bucket assigned by its own start.  The pre-fix behavior clamped a
    source bucket that STARTED before the window into the same step
    bucket as the first in-window one, so an unaligned ``start`` got a
    first value whose data window preceded the request; now the
    pre-start bucket keeps its own grid slot and only its emitted
    TIMESTAMP clamps to ``start`` (via :func:`_emit`)."""
    buckets: dict = {}
    for bt, mn, mx, sm, cnt in quads:
        b = bt // step_ms * step_ms
        cur = buckets.get(b)
        if cur is None:
            buckets[b] = [mn, mx, sm, cnt]
        else:
            if mn < cur[0]:
                cur[0] = mn
            if mx > cur[1]:
                cur[1] = mx
            cur[2] += sm
            cur[3] += cnt
    return _emit(buckets, agg, start_ms)


def _aggregate_sketches(digests, start_ms, step_ms, q):
    """Step-align per-tier-bucket digests: merge every digest landing
    in one epoch-anchored step bucket, emit its quantile.  Same grid
    and first-bucket clamp as the quads path."""
    from tpudash.analytics.sketch import QuantileSketch

    buckets: dict = {}
    for bt, sk in digests:
        buckets.setdefault(bt // step_ms * step_ms, []).append(sk)
    out = []
    for b in sorted(buckets):
        sks = buckets[b]
        sk = sks[0] if len(sks) == 1 else QuantileSketch.merged(sks)
        v = sk.quantile(q)
        if v != v:
            continue
        out.append((gorilla.ms_to_ts(max(b, start_ms)), v))
    return out


def _emit(buckets: dict, agg: str, start_ms: int = 0):
    out = []
    for b in sorted(buckets):
        mn, mx, sm, cnt = buckets[b]
        if cnt <= 0:
            continue
        if agg == "min":
            v = mn
        elif agg == "max":
            v = mx
        else:
            v = sm / cnt
        # the epoch-anchored grid may slot data into a bucket starting
        # before the window (its tail reaches in): report it AT the
        # window edge, never before it
        out.append((gorilla.ms_to_ts(max(b, start_ms)), v))
    return out


def range_query(
    store,
    key: str,
    cols: "list[str] | None" = None,
    start_s: "float | None" = None,
    end_s: "float | None" = None,
    step_s: "float | None" = None,
    agg: str = "mean",
    max_points: int = DEFAULT_POINTS,
) -> dict:
    """Aligned series for one key over [start, end].

    Returns ``{"series": {col: [(ts_s, value), ...]}, "resolution",
    "start_s", "end_s", "step_s", "agg"}``.  Defaults: ``end`` = the
    store's newest sample, ``start`` = end − 1h, ``cols`` = every
    column the series carries, ``step`` = whatever fits the budget.
    ``agg=p50|p95|p99`` serves quantiles from the sketch rollups; with
    ``key = FLEET_SERIES`` that is the fleet DISTRIBUTION (all chips'
    samples), not the fleet-average row.  Raises ValueError on a bad
    aggregate/window (the HTTP layer maps it to 400)."""
    from tpudash.tsdb.store import FLEET_SERIES

    win = resolve_window(store, start_s, end_s, step_s, max_points, agg)
    if win["empty"]:
        # empty store: a well-formed empty answer, not an error
        return {
            "series": {c: [] for c in (cols or [])},
            "resolution": "raw",
            "start_s": start_s or 0.0,
            "end_s": end_s or 0.0,
            "step_s": step_s or 0.0,
            "agg": agg,
        }
    start_ms, end_ms = win["start_ms"], win["end_ms"]
    step_ms, tier = win["step_ms"], win["tier"]
    max_points = max(1, min(int(max_points), MAX_POINTS))
    window = max(1, end_ms - start_ms)
    min_step = -(-window // max(1, max_points - 1))
    q = QUANTILE_AGGS.get(agg)
    if cols is None:
        cols = store.series_cols(key)
    series: dict = {}
    for col in cols:
        if q is not None:
            sk_key = ALL_KEY if key == FLEET_SERIES else key
            digests = store.sketch_series_window(
                tier, sk_key, col, start_ms, end_ms
            )
            series[col] = _aggregate_sketches(
                digests, start_ms, max(step_ms, TIER_1M_MS), q
            )
        elif tier == 0:
            pts = store.raw_window(key, col, start_ms, end_ms)
            eff_step = step_ms
            if not eff_step and len(pts) > max_points:
                eff_step = min_step
            series[col] = _aggregate_raw(
                pts, start_ms, end_ms, eff_step, agg
            )
        else:
            quads = store.rollup_window(tier, key, col, start_ms, end_ms)
            series[col] = _aggregate_quads(
                quads, start_ms, step_ms or tier, agg
            )
    out = {
        "series": series,
        "resolution": win["resolution"],
        "start_s": start_ms / 1000.0,
        "end_s": end_ms / 1000.0,
        "step_s": (step_ms or 0) / 1000.0,
        "agg": agg,
    }
    # honest degrade (the federation contract, applied to the cold
    # tier): a window reaching below hot coverage while the object
    # store is unreachable may be missing archived history — the answer
    # ships what the hot tier has, flagged, never a 500 and never a
    # silent truncation.  Checked AFTER the reads so a store that went
    # dark mid-query still marks the result.
    degrade = getattr(store, "cold_degrade_info", None)
    info = degrade(start_ms) if degrade is not None else None
    if info is not None:
        out["partial"] = True
        out["cold"] = info
    return out
