"""Range queries over the tsdb: series select, step alignment,
aggregate choice, point budget.

The one read surface everything shares — the sparkline builder
(service._trends), the drill-down chip trends, and ``GET /api/range``
all call :func:`range_query`, so resolution selection and step
alignment have exactly one implementation to test.

Resolution selection: the finest tier still *covering the window's
start* wins — raw first, then 1m, then 10m — except that a step wide
enough for a rollup tier (≥ its bucket width) prefers the rollup: the
answer is identical (rollups are exact min/max/sum/count) and the read
decodes 60–600× fewer points.  When nothing covers the start (asked
for more history than exists), the tier reaching furthest back serves
what it has — a shorter graph, never an error.

The point budget is a hard ceiling: a query whose natural resolution
would return more than ``max_points`` is step-widened until it fits,
so one ``/api/range`` call can never ship (or force the server to
decode) an unbounded payload.
"""

from __future__ import annotations

from tpudash.tsdb import gorilla
from tpudash.tsdb.rollup import TIER_1M_MS, TIER_10M_MS

AGGREGATES = ("mean", "min", "max")

#: default / ceiling for one query's returned points per column
DEFAULT_POINTS = 500
MAX_POINTS = 5000

_TIER_NAME = {0: "raw", TIER_1M_MS: "1m", TIER_10M_MS: "10m"}


def _choose_tier(store, start_ms: int, step_ms: int) -> int:
    """The tier this query reads (0 = raw).  Reach-back is judged on
    each tier's *source*-time earliest sample; a tier that merely
    reaches as far back as raw never beats raw (ties prefer finer)."""
    earliest = {t: store.earliest_ms(t) for t in (0, TIER_1M_MS, TIER_10M_MS)}
    e_raw = earliest[0]
    # a step at least one bucket wide prefers the exact-but-cheaper
    # rollup read — provided the rollup reaches back as far as raw does
    for tier in (TIER_10M_MS, TIER_1M_MS):
        e = earliest[tier]
        if (
            step_ms >= tier
            and e is not None
            and (e_raw is None or e <= max(start_ms, e_raw))
        ):
            return tier
    if e_raw is not None and e_raw <= start_ms:
        return 0
    # raw doesn't cover the start (expired, or asked before history
    # began): the tier reaching furthest back wins; ties prefer finer
    candidates = [(e, t) for t, e in earliest.items() if e is not None]
    if not candidates:
        return 0
    return min(candidates)[1]


def _aggregate_raw(points, start_ms, end_ms, step_ms, agg):
    """Step-align raw (ts, value) points; NaN samples are skipped."""
    if step_ms <= 0:
        return [
            (gorilla.ms_to_ts(t), v) for t, v in points if v == v
        ]
    buckets: dict = {}
    for t, v in points:
        if v != v:
            continue
        b = start_ms + (t - start_ms) // step_ms * step_ms
        cur = buckets.get(b)
        if cur is None:
            buckets[b] = [v, v, v, 1]
        else:
            if v < cur[0]:
                cur[0] = v
            if v > cur[1]:
                cur[1] = v
            cur[2] += v
            cur[3] += 1
    return _emit(buckets, agg)


def _aggregate_quads(quads, start_ms, step_ms, agg):
    """Step-align rollup quads — exact: min of mins, max of maxes,
    sum/count for the mean.  A source bucket that STARTED before the
    window (but reaches into it) clamps to the first step bucket, so
    emitted timestamps always lie inside [start, end]."""
    buckets: dict = {}
    for bt, mn, mx, sm, cnt in quads:
        off = bt - start_ms
        b = start_ms if off < 0 else start_ms + off // step_ms * step_ms
        cur = buckets.get(b)
        if cur is None:
            buckets[b] = [mn, mx, sm, cnt]
        else:
            if mn < cur[0]:
                cur[0] = mn
            if mx > cur[1]:
                cur[1] = mx
            cur[2] += sm
            cur[3] += cnt
    return _emit(buckets, agg)


def _emit(buckets: dict, agg: str):
    out = []
    for b in sorted(buckets):
        mn, mx, sm, cnt = buckets[b]
        if cnt <= 0:
            continue
        if agg == "min":
            v = mn
        elif agg == "max":
            v = mx
        else:
            v = sm / cnt
        out.append((gorilla.ms_to_ts(b), v))
    return out


def range_query(
    store,
    key: str,
    cols: "list[str] | None" = None,
    start_s: "float | None" = None,
    end_s: "float | None" = None,
    step_s: "float | None" = None,
    agg: str = "mean",
    max_points: int = DEFAULT_POINTS,
) -> dict:
    """Aligned series for one key over [start, end].

    Returns ``{"series": {col: [(ts_s, value), ...]}, "resolution",
    "start_s", "end_s", "step_s", "agg"}``.  Defaults: ``end`` = the
    store's newest sample, ``start`` = end − 1h, ``cols`` = every
    column the series carries, ``step`` = whatever fits the budget.
    Raises ValueError on a bad aggregate/window (the HTTP layer maps
    it to 400)."""
    if agg not in AGGREGATES:
        raise ValueError(f"agg must be one of {AGGREGATES}, not {agg!r}")
    max_points = max(1, min(int(max_points), MAX_POINTS))
    latest = store.latest_ms()
    end_ms = gorilla.ts_to_ms(end_s) if end_s is not None else latest
    if end_ms is None:
        # empty store: a well-formed empty answer, not an error
        return {
            "series": {c: [] for c in (cols or [])},
            "resolution": "raw",
            "start_s": start_s or 0.0,
            "end_s": end_s or 0.0,
            "step_s": step_s or 0.0,
            "agg": agg,
        }
    start_ms = (
        gorilla.ts_to_ms(start_s)
        if start_s is not None
        else end_ms - 3_600_000
    )
    if end_ms < start_ms:
        raise ValueError("end precedes start")
    window = max(1, end_ms - start_ms)
    step_ms = int(step_s * 1000) if step_s else 0
    if step_ms < 0:
        raise ValueError("step must be positive")
    # the budget is a ceiling, whatever step the caller asked for
    min_step = -(-window // max_points)  # ceil
    if step_ms and step_ms < min_step:
        step_ms = min_step
    tier = _choose_tier(store, start_ms, step_ms)
    if tier != 0:
        if step_ms < tier:
            step_ms = tier  # a rollup can't answer finer than its bucket
        if step_ms < min_step:
            # the budget is a ceiling on EVERY tier: a 30-day stepless
            # query must not ship window/tier (~4300) bucket points just
            # because the rollup resolution happens to be fine
            step_ms = min_step
    if cols is None:
        cols = store.series_cols(key)
    series: dict = {}
    for col in cols:
        if tier == 0:
            pts = store.raw_window(key, col, start_ms, end_ms)
            eff_step = step_ms
            if not eff_step and len(pts) > max_points:
                eff_step = min_step
            series[col] = _aggregate_raw(
                pts, start_ms, end_ms, eff_step, agg
            )
        else:
            quads = store.rollup_window(tier, key, col, start_ms, end_ms)
            series[col] = _aggregate_quads(
                quads, start_ms, step_ms or tier, agg
            )
    return {
        "series": series,
        "resolution": _TIER_NAME[tier],
        "start_s": start_ms / 1000.0,
        "end_s": end_ms / 1000.0,
        "step_s": (step_ms or 0) / 1000.0,
        "agg": agg,
    }
