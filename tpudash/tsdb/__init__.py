"""tpudash.tsdb — embedded compressed time-series store.

Layers (each its own module, each independently tested):

- :mod:`tpudash.tsdb.gorilla` — delta-of-delta + XOR bit codec;
- :mod:`tpudash.tsdb.store` — head chunks → sealed blocks → CRC-framed
  append-only segment files with torn-tail recovery;
- :mod:`tpudash.tsdb.rollup` — tiered downsampling (raw → 1m → 10m,
  min/max/sum/count) with per-tier retention;
- :mod:`tpudash.tsdb.query` — the range-query layer (series select,
  step alignment, aggregate choice, point budget) that the sparklines,
  drill-downs, and ``GET /api/range`` consume;
- :mod:`tpudash.tsdb.snapshot` — online snapshots (hardlinked segment
  sets + CRC-framed manifest), verified restore, retention-aware GC;
- :mod:`tpudash.tsdb.follower` — read-only hot-standby mode tailing
  another instance's segment directory with measured replication lag;
- :mod:`tpudash.tsdb.objstore` — pluggable object-store interface for
  the cold tier (filesystem backend built in, fault hooks for chaos);
- :mod:`tpudash.tsdb.cold` — immutable, self-verifying archive bundles
  (per-section CRCs + whole-bundle digest) and the read-through tier
  that folds them behind hot coverage with a bounded, digest-checked
  local cache; corrupt bundles are quarantined, never served;
- :mod:`tpudash.tsdb.compact` — the compactor folding sealed segment
  files into bundles off the seal thread: staged locally, uploaded
  with decorrelated backoff, verified by digest read-back BEFORE the
  local segments become reclaim-eligible.

``python -m tpudash.tsdb drill`` is the crash chaos drill (kill -9 mid
segment-append, assert sealed data survives); ``snapshot``/``restore``
are the backup surface; ``compact`` is the cold tier's one-shot sweep;
CI runs the drills (including ``python -m tpudash.chaos coldstorm``)
every PR.
"""

from tpudash.tsdb.store import FLEET_SERIES, TSDB

__all__ = ["TSDB", "FLEET_SERIES"]
