"""The cold tier: immutable, self-verifying archive bundles in an
object store, read transparently behind the hot store.

An **archive bundle** is one object folding a set of sealed segment
files (tpudash/tsdb/compact.py decides which).  Layout::

    [TSB1 frames — the segment records VERBATIM: type 1 raw block,
     2 rollup, 4 sketch — same codecs, same per-record CRC framing]
    [TSB1 frame type 5: the bundle manifest (JSON)]
    [footer: manifest offset (u64) + b"TDBF"]

The manifest is the bundle's sparse index: one entry per section
(frame offset/length/type/tier/time-bounds/CRC), the source segment
files it folds (name + byte count — segment reclaim keys on these),
the series key/column unions, and a whole-bundle SHA-256 over every
byte before the manifest frame.  A reader locates the sketch sections
for a window from the manifest alone — a 90-day quantile query never
touches (or decodes) a raw section.

Trust model — verify, never assume:

- the manifest frame carries the TSB1 CRC; a torn upload fails here;
- the whole-bundle digest is checked on every download into the local
  bundle cache (and by the compactor's read-back before any local
  segment becomes reclaim-eligible);
- every section re-checks its frame CRC at parse time (bit-rot in the
  cache re-downloads once; bit-rot in the store quarantines).

A bundle failing any check is **quarantined**: dropped from the
catalog, never served, remembered via a ``quarantine/`` marker object,
and surfaced as the ``cold_corrupt`` synthesized alert.  Its source
segments count as uncovered again, so — while they still exist — the
next compaction run rebuilds and replaces the bad object (the self-heal
the coldstorm drill pins).

An unreachable store never raises into a query: :class:`ColdTier`
marks itself ``unreachable``, serves what the local cache still holds,
and the hot store's answer degrades to ``partial:true`` (the federation
degrade contract; see query.py / server.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import struct
import threading
import time
import zlib

from tpudash import wireids
from tpudash.tsdb.objstore import ObjectStoreError
from tpudash.tsdb.store import (
    _FRAME_HDR,
    _MAGIC,
    _REC_BLOCK,
    _REC_ROLLUP,
    _REC_SKETCH,
    _parse_block,
    _parse_rollup,
    _parse_sketch,
)

log = logging.getLogger(__name__)

#: bundle-manifest record type inside the shared TSB1 framing — 5, the
#: next free type (1/2/4 = segment records reused verbatim as bundle
#: sections, 3 = snapshot.py's MANIFEST); record types stay globally
#: unique so any tool dispatches on type alone, whichever file it reads
_REC_BUNDLE_MANIFEST = wireids.TSB1_REC_BUNDLE_MANIFEST
#: bundle footer: manifest frame offset + magic, fixed at EOF so a
#: reader finds the manifest with two ranged reads (tail, then frame)
_FOOTER = struct.Struct("<Q4s")
_FOOTER_MAGIC = wireids.TDBF_FOOTER_MAGIC

BUNDLE_PREFIX = "bundles/"
QUARANTINE_PREFIX = "quarantine/"
BUNDLE_SUFFIX = ".tdb"

_SECTION_PARSERS = {
    _REC_BLOCK: _parse_block,
    _REC_ROLLUP: _parse_rollup,
    _REC_SKETCH: _parse_sketch,
}
_SECTION_NAMES = {_REC_BLOCK: "raw", _REC_ROLLUP: "rollup", _REC_SKETCH: "sketch"}


class BundleError(Exception):
    """A bundle failed validation — the message names the check."""


def build_bundle(sections, sources, created_ms, keys, cols):
    """Serialize one archive bundle.  ``sections`` is a list of
    ``(rec_type, tier_ms, t0, t1, payload_bytes)`` — the payloads are
    segment-record payloads verbatim; ``sources`` is
    ``[{"name", "bytes"}]`` for the segment files folded in.  Returns
    ``(bundle_bytes, manifest_doc)``."""
    parts: "list[bytes]" = []
    index: "list[dict]" = []
    off = 0
    t0 = None
    t1 = 0
    counts = {"raw": 0, "rollup": 0, "sketch": 0}
    for rec_type, tier_ms, s_t0, s_t1, payload in sections:
        frame = _FRAME_HDR.pack(
            _MAGIC, rec_type, len(payload), zlib.crc32(payload)
        ) + payload
        parts.append(frame)
        index.append(
            {
                "off": off,
                "len": len(frame),
                "type": int(rec_type),
                "tier": int(tier_ms),
                "t0": int(s_t0),
                "t1": int(s_t1),
                "crc": zlib.crc32(payload),
            }
        )
        counts[_SECTION_NAMES[rec_type]] += 1
        off += len(frame)
        t0 = s_t0 if t0 is None else min(t0, s_t0)
        t1 = max(t1, s_t1)
    body = b"".join(parts)
    manifest = {
        "version": 1,
        "created_ms": int(created_ms),
        "t0": int(t0 or 0),
        "t1": int(t1),
        "sections": index,
        "sources": [
            {"name": s["name"], "bytes": int(s["bytes"])} for s in sources
        ],
        "keys": sorted(keys),
        "cols": sorted(cols),
        "counts": counts,
        "digest": hashlib.sha256(body).hexdigest(),
    }
    payload = json.dumps(manifest, separators=(",", ":")).encode()
    mframe = _FRAME_HDR.pack(
        _MAGIC, _REC_BUNDLE_MANIFEST, len(payload), zlib.crc32(payload)
    ) + payload
    footer = _FOOTER.pack(len(body), _FOOTER_MAGIC)
    return body + mframe + footer, manifest


def _parse_manifest_frame(frame: bytes) -> dict:
    if len(frame) < _FRAME_HDR.size:
        raise BundleError("manifest frame shorter than its header")
    try:
        magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(frame, 0)
    except struct.error as e:  # belt-and-braces: length checked above
        raise BundleError(f"manifest frame unreadable: {e}") from e
    payload = frame[_FRAME_HDR.size : _FRAME_HDR.size + plen]
    if (
        magic != _MAGIC
        or rec_type != _REC_BUNDLE_MANIFEST
        or len(payload) != plen
        or zlib.crc32(payload) != crc
    ):
        raise BundleError("manifest frame failed magic/CRC validation")
    try:
        doc = json.loads(payload)
    except ValueError as e:
        raise BundleError(f"manifest payload is not JSON: {e}") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("sections"), list):
        raise BundleError("manifest missing its section index")
    # shape-validate the index HERE, so every downstream consumer
    # (_load_section seeks, _sections_for range checks, covers_segment,
    # _bundle_size) can subscript entries without a malformed manifest
    # escaping KeyError/TypeError past their BundleError handling
    for sec in doc["sections"]:
        if not isinstance(sec, dict):
            raise BundleError("manifest section entry is not an object")
        for field in ("off", "len", "type", "tier", "t0", "t1"):
            v = sec.get(field)
            if not isinstance(v, int) or isinstance(v, bool):
                raise BundleError(
                    f"manifest section {field!r} is not an integer"
                )
        if sec["off"] < 0 or sec["len"] < 0:
            raise BundleError("manifest section offset/length negative")
    sources = doc.get("sources", [])
    if not isinstance(sources, list):
        raise BundleError("manifest sources is not a list")
    for src in sources:
        if not isinstance(src, dict):
            raise BundleError("manifest source entry is not an object")
        if not isinstance(src.get("bytes", 0), int):
            raise BundleError("manifest source bytes is not an integer")
    for field in ("keys", "cols"):
        if not isinstance(doc.get(field, []), list):
            raise BundleError(f"manifest {field} is not a list")
    return doc


def parse_bundle(data: bytes, verify_digest: bool = True) -> dict:
    """Validate a whole bundle image and return its manifest.  Checks
    footer magic, manifest frame CRC, and (by default) the whole-bundle
    SHA-256 over the section bytes.  Raises :class:`BundleError` on the
    first mismatch — a bundle is trusted whole or not at all."""
    if len(data) < _FOOTER.size + _FRAME_HDR.size:
        raise BundleError("bundle shorter than footer + manifest frame")
    try:
        moff, magic = _FOOTER.unpack_from(data, len(data) - _FOOTER.size)
    except struct.error as e:  # belt-and-braces: length checked above
        raise BundleError(f"bundle footer unreadable: {e}") from e
    if magic != _FOOTER_MAGIC or moff > len(data) - _FOOTER.size:
        raise BundleError("bundle footer failed magic/offset validation")
    doc = _parse_manifest_frame(data[moff : len(data) - _FOOTER.size])
    if verify_digest:
        got = hashlib.sha256(data[:moff]).hexdigest()
        if got != doc.get("digest"):
            raise BundleError(
                f"whole-bundle digest mismatch (manifest "
                f"{str(doc.get('digest'))[:12]}…, bytes {got[:12]}…)"
            )
    return doc


def read_remote_manifest(store, key: str) -> dict:
    """Fetch ONLY a bundle's manifest from the store (two ranged reads
    — footer, then manifest frame).  CRC-validated; the whole-bundle
    digest is deferred to download time."""
    size = store.size(key)
    if size < _FOOTER.size + _FRAME_HDR.size:
        raise BundleError(f"{key}: object shorter than a bundle footer")
    tail = store.get(key, start=size - _FOOTER.size, length=_FOOTER.size)
    if len(tail) != _FOOTER.size:
        raise BundleError(f"{key}: short footer read")
    moff, magic = _FOOTER.unpack(tail)
    if magic != _FOOTER_MAGIC or moff > size - _FOOTER.size:
        raise BundleError(f"{key}: footer failed magic/offset validation")
    frame = store.get(key, start=moff, length=size - _FOOTER.size - moff)
    return _parse_manifest_frame(frame)


class ColdTier:
    """Read surface over the archive catalog + the bounded local bundle
    cache.  Attached to a :class:`~tpudash.tsdb.store.TSDB` via
    ``store.attach_cold``; every query fold happens behind the hot
    store's own windows (store.py clamps cold reads to strictly before
    hot coverage, so nothing double-counts).

    Thread contract: ``_lock`` guards catalog/counters (pointer swaps
    only, never I/O); ``_io_lock`` serializes cache downloads.  Query
    callers are executor/seal/compactor threads — never the event loop.
    """

    def __init__(
        self,
        store,
        cache_dir: str,
        cache_max_bytes: int = 256 << 20,
        refresh_interval_s: float = 15.0,
    ) -> None:
        self.store = store
        self.cache_dir = cache_dir
        self.cache_max_bytes = max(1 << 20, int(cache_max_bytes))
        self.refresh_interval_s = max(0.5, float(refresh_interval_s))
        self._lock = threading.RLock()
        self._io_lock = threading.Lock()
        #: bundle key → manifest (verified-shape, digest checked on
        #: download); quarantined keys live in _quarantine instead
        self._catalog: "dict[str, dict]" = {}
        self._quarantine: "dict[str, str]" = {}
        self._last_refresh_mono: "float | None" = None
        self._catalog_version = 0
        self._section_memo: "dict[tuple, list]" = {}
        #: parsed-section cache: (key, off) → decoded block (FIFO-bounded)
        self._parsed: "dict[tuple, object]" = {}
        self._parsed_max = 512
        self.unreachable = False
        self.last_error: "str | None" = None
        #: compactor registers itself here so one status() tells the whole
        #: cold story (reads + writes) on /api/timings
        self.compactor = None
        #: invoked on every catalog change; the hot store wires its
        #: version bump here so range-result ETags see new archives
        self.on_change = None
        self.counters = {
            "refreshes": 0,
            "bundle_fetches": 0,
            "cache_hits": 0,
            "cache_evictions": 0,
            "sections_parsed_raw": 0,
            "sections_parsed_rollup": 0,
            "sections_parsed_sketch": 0,
            "quarantined_total": 0,
        }

    # -- catalog -------------------------------------------------------------
    def refresh(self, force: bool = False) -> None:
        """Interval-gated catalog sync: list the store, pull manifests
        for unseen bundles, honor quarantine markers.  An unreachable
        store flips ``unreachable`` and keeps the cached catalog —
        queries degrade, they do not fail."""
        now = time.monotonic()
        with self._lock:
            if (
                not force
                and self._last_refresh_mono is not None
                and now - self._last_refresh_mono < self.refresh_interval_s
            ):
                return
            self._last_refresh_mono = now
        try:
            keys = self.store.list(BUNDLE_PREFIX)
            markers = set(self.store.list(QUARANTINE_PREFIX))
        except ObjectStoreError as e:
            self._mark_unreachable(str(e))
            return
        with self._lock:
            was_unreachable = self.unreachable
            self.unreachable = False
            self.last_error = None
            self.counters["refreshes"] += 1
            known = set(self._catalog) | set(self._quarantine)
            if was_unreachable:
                # reachability is part of every range answer (partial
                # flag), so the flip must invalidate range ETags too
                self._bump_catalog_locked()
        if was_unreachable:
            log.info("cold store reachable again (%s)", self.store.describe())
        marked = {
            BUNDLE_PREFIX + os.path.basename(m)[: -len(".marker")]
            for m in markers
            if m.endswith(".marker")
        }
        for key in keys:
            if not key.endswith(BUNDLE_SUFFIX):
                continue  # upload husk or foreign object: ignorable
            if key in marked and key not in known:
                with self._lock:
                    self._quarantine[key] = "quarantine marker present"
                continue
            if key in known:
                continue
            try:
                man = read_remote_manifest(self.store, key)
            except BundleError as e:
                self.quarantine(key, str(e))
                continue
            except ObjectStoreError as e:
                self._mark_unreachable(str(e))
                return
            self._register_locked_entry(key, man)
        # bundles deleted out from under us (archive retention by an
        # operator) fall out of the catalog on the next refresh
        present = set(keys)
        with self._lock:
            for key in [k for k in self._catalog if k not in present]:
                del self._catalog[k]
                self._bump_catalog_locked()

    def _mark_unreachable(self, err: str) -> None:
        """Flip to unreachable, bumping the catalog version on the
        transition: range ETags hash the store version, and an answer
        that just became ``partial: true`` must not 304 as the old
        complete body."""
        with self._lock:
            flipped = not self.unreachable
            self.unreachable = True
            self.last_error = err
            if flipped:
                self._bump_catalog_locked()

    def _bump_catalog_locked(self) -> None:
        self._catalog_version += 1
        self._section_memo.clear()
        cb = self.on_change
        if cb is not None:
            cb()

    def _register_locked_entry(self, key: str, manifest: dict) -> None:
        with self._lock:
            self._catalog[key] = manifest
            self._bump_catalog_locked()

    def register(self, key: str, manifest: dict) -> None:
        """Compactor hand-off after a verified upload: the bundle enters
        the catalog (and leaves quarantine — re-compaction over the same
        sources is the self-heal path for a corrupt object)."""
        with self._lock:
            healed = key in self._quarantine
            self._quarantine.pop(key, None)
            self._catalog[key] = manifest
            self._bump_catalog_locked()
        if healed:
            with contextlib.suppress(ObjectStoreError):
                self.store.delete(_marker_key(key))
            log.info("cold bundle %s healed by re-compaction", key)

    def quarantine(self, key: str, reason: str) -> None:
        """Never serve this bundle again (until a verified replacement
        lands): drop from catalog, drop its cache file, persist a
        marker so restarts remember, and count it for the
        ``cold_corrupt`` alert."""
        with self._lock:
            already = key in self._quarantine
            self._catalog.pop(key, None)
            self._quarantine[key] = reason
            self._bump_catalog_locked()
            if not already:
                self.counters["quarantined_total"] += 1
        self._invalidate_cache(key)
        if not already:
            log.warning("cold bundle %s QUARANTINED: %s", key, reason)
            with contextlib.suppress(ObjectStoreError):
                self.store.put(
                    _marker_key(key),
                    json.dumps({"key": key, "reason": reason}).encode(),
                )

    # -- bounded, digest-checked local bundle cache --------------------------
    def _cache_path(self, key: str) -> str:
        return os.path.join(self.cache_dir, os.path.basename(key))

    def _invalidate_cache(self, key: str) -> None:
        with contextlib.suppress(OSError):
            os.remove(self._cache_path(key))
        with self._lock:
            for pk in [p for p in self._parsed if p[0] == key]:
                del self._parsed[pk]

    def _ensure_local(self, key: str, manifest: dict) -> "str | None":
        """The bundle's bytes on local disk, downloading (and digest-
        checking) on miss.  None = store unreachable (degrade) or the
        bundle failed verification (quarantined)."""
        path = self._cache_path(key)
        if _cache_file_ok(path, manifest):
            with self._lock:
                self.counters["cache_hits"] += 1
            with contextlib.suppress(OSError):
                os.utime(path)  # LRU recency
            return path
        with self._io_lock:  # tpulint: allow[blocking-under-lock] dedicated cache-download lock: serializes fetches only; catalog reads ride _lock, never this
            # re-check under the lock: a racing fetch may have landed it
            if _cache_file_ok(path, manifest):
                return path
            try:
                data = self.store.get(key)
            except ObjectStoreError as e:
                self._mark_unreachable(str(e))
                return None
            try:
                parse_bundle(data, verify_digest=True)
            except BundleError as e:
                self.quarantine(key, str(e))
                return None
            try:
                os.makedirs(self.cache_dir, exist_ok=True)
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError as e:
                # cache volume trouble: the parsed data is still good,
                # but without a cache file section reads can't seek —
                # degrade this read
                with self._lock:
                    self.last_error = f"bundle cache write failed: {e}"
                return None
            with self._lock:
                self.counters["bundle_fetches"] += 1
        self._evict_cache(keep=os.path.basename(path))
        return path

    def _evict_cache(self, keep: str) -> None:
        """Drop oldest cached bundles until the cache fits its budget
        (the just-used file always survives)."""
        try:
            names = [
                n for n in os.listdir(self.cache_dir)
                if n.endswith(BUNDLE_SUFFIX)
            ]
            entries = []
            total = 0
            for n in names:
                full = os.path.join(self.cache_dir, n)
                st = os.stat(full)
                entries.append((st.st_mtime, st.st_size, n, full))
                total += st.st_size
            entries.sort()
            for _mt, sz, n, full in entries:
                if total <= self.cache_max_bytes or n == keep:
                    continue
                with contextlib.suppress(OSError):
                    os.remove(full)
                    total -= sz
                    with self._lock:
                        self.counters["cache_evictions"] += 1
        except OSError:
            return

    # -- section access ------------------------------------------------------
    def _sections_for(self, rec_type: int, tier_ms: int,
                      start_ms: int, end_ms: int) -> list:
        """Manifest-level sparse-index scan: every (bundle key,
        manifest, section) of the type/tier intersecting the window.
        Memoized per catalog version — the fleet-distribution path asks
        the same window once per series key."""
        memo_key = (rec_type, tier_ms, start_ms, end_ms)
        with self._lock:
            got = self._section_memo.get(memo_key)
            if got is not None:
                return got
            catalog = list(self._catalog.items())
        out = []
        for key, man in catalog:
            if man.get("t1", 0) < start_ms or man.get("t0", 0) > end_ms:
                continue
            for sec in man.get("sections", ()):
                if (
                    sec.get("type") == rec_type
                    and sec.get("tier") == tier_ms
                    and sec.get("t1", 0) >= start_ms
                    and sec.get("t0", 0) <= end_ms
                ):
                    out.append((key, man, sec))
        out.sort(key=lambda item: item[2].get("t0", 0))
        with self._lock:
            if len(self._section_memo) > 256:
                self._section_memo.clear()
            self._section_memo[memo_key] = out
        return out

    def _load_section(self, key: str, manifest: dict, sec: dict):
        """Decode one section (frame-CRC-verified).  A cache-local
        failure re-downloads once (digest-checked); a failure that
        survives the re-download is store-side corruption →
        quarantine.  None = unavailable (degraded or quarantined)."""
        cache_key = (key, sec["off"])
        with self._lock:
            got = self._parsed.get(cache_key)
        if got is not None:
            return got
        for attempt in (0, 1):
            path = self._ensure_local(key, manifest)
            if path is None:
                return None
            try:
                with open(path, "rb") as f:
                    f.seek(sec["off"])
                    frame = f.read(sec["len"])
                magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(frame, 0)
                payload = frame[_FRAME_HDR.size : _FRAME_HDR.size + plen]
                if (
                    magic != _MAGIC
                    or rec_type != sec["type"]
                    or len(payload) != plen
                    or zlib.crc32(payload) != crc
                ):
                    raise BundleError("section frame failed magic/CRC")
                obj = _SECTION_PARSERS[rec_type](payload)
            except (BundleError, OSError, ValueError, KeyError,
                    struct.error) as e:
                self._invalidate_cache(key)
                if attempt == 0:
                    continue  # cache bit-rot: one digest-checked refetch
                self.quarantine(key, f"section @{sec['off']}: {e}")
                return None
            with self._lock:
                if len(self._parsed) >= self._parsed_max:
                    self._parsed.pop(next(iter(self._parsed)))
                self._parsed[cache_key] = obj
                self.counters[
                    f"sections_parsed_{_SECTION_NAMES[rec_type]}"
                ] += 1
            return obj
        return None

    # -- query surfaces (folded in by store.py behind hot coverage) ----------
    def rollup_window(self, tier_ms: int, key: str, col: str,
                      start_ms: int, end_ms: int) -> list:
        """(bucket, mn, mx, sm, cnt) quads for one series from archive
        rollup sections intersecting the window."""
        self.refresh()
        quads: list = []
        for bkey, man, sec in self._sections_for(
            _REC_ROLLUP, tier_ms, start_ms, end_ms
        ):
            r = self._load_section(bkey, man, sec)
            if r is None:
                continue
            quads.extend(
                q for q in r.series_quads(key, col)
                if q[0] + tier_ms - 1 >= start_ms and q[0] <= end_ms
            )
        return quads

    def sketch_digests(self, tier_ms: int, key: str, col: str,
                       start_ms: int, end_ms: int):
        """``([(bucket_ms, digest_bytes)], covered_hi_ms)`` from archive
        sketch sections.  ``covered_hi_ms`` is the newest source stamp
        the loaded sections cover — the hot store's gap oracle starts
        AFTER it, so a sketch-covered archive window is answered from
        the sparse index alone (never a raw-section decode)."""
        self.refresh()
        out: list = []
        covered_hi = 0
        for bkey, man, sec in self._sections_for(
            _REC_SKETCH, tier_ms, start_ms, end_ms
        ):
            s = self._load_section(bkey, man, sec)
            if s is None:
                continue
            for b, raw in s.series(key, col):
                if b + tier_ms - 1 >= start_ms and b <= end_ms:
                    out.append((b, raw))
            covered_hi = max(covered_hi, sec.get("t1", 0))
        return out, covered_hi

    def raw_points(self, key: str, col: str,
                   start_ms: int, end_ms: int) -> list:
        """(ts_ms, value) raw points from archive raw sections — the
        full-fidelity read for replay over expired local history."""
        self.refresh()
        pts: list = []
        for bkey, man, sec in self._sections_for(
            _REC_BLOCK, 0, start_ms, end_ms
        ):
            b = self._load_section(bkey, man, sec)
            if b is None:
                continue
            got = b.series_points(key, col)
            if got is None:
                continue
            ts_list, vals = got
            pts.extend(
                (t, v) for t, v in zip(ts_list, vals)
                if start_ms <= t <= end_ms
            )
        return pts

    # -- horizon / coverage --------------------------------------------------
    def earliest_ms(self, tier_ms: int = 0) -> "int | None":
        """Oldest archived source stamp for a tier (0 = raw), from
        manifests alone — quarantined bundles never count."""
        lo = None
        want = (
            {_REC_BLOCK} if tier_ms == 0 else {_REC_ROLLUP, _REC_SKETCH}
        )
        with self._lock:
            manifests = list(self._catalog.values())
        for man in manifests:
            for sec in man.get("sections", ()):
                if sec.get("type") in want and sec.get("tier") == tier_ms:
                    t0 = sec.get("t0")
                    if t0 is not None and (lo is None or t0 < lo):
                        lo = t0
        return lo

    def latest_ms(self) -> "int | None":
        with self._lock:
            t1s = [m.get("t1", 0) for m in self._catalog.values()]
        return max(t1s) if t1s else None

    def series_keys(self) -> "set[str]":
        out: set = set()
        with self._lock:
            for man in self._catalog.values():
                out.update(man.get("keys", ()))
        return out

    def series_cols(self) -> "list[str]":
        cols: dict = {}
        with self._lock:
            for man in self._catalog.values():
                for c in man.get("cols", ()):
                    cols[c] = None
        return list(cols)

    def covers_segment(self, name: str, nbytes: int) -> bool:
        """Is this segment file's full byte range folded into a
        VERIFIED, non-quarantined bundle?  The reclaim gate — a dark
        store (stale catalog) answers False and reclaim pauses rather
        than losing data."""
        with self._lock:
            for man in self._catalog.values():
                for src in man.get("sources", ()):
                    if src.get("name") == name and src.get("bytes", 0) >= nbytes:
                        return True
        return False

    def covered_names(self) -> "set[str]":
        with self._lock:
            return {
                src.get("name")
                for man in self._catalog.values()
                for src in man.get("sources", ())
            }

    # -- observability / lifecycle -------------------------------------------
    def status(self) -> dict:
        """One dict for stats() → /api/timings / healthz / alerts."""
        with self._lock:
            bundles = len(self._catalog)
            bundle_bytes = sum(
                _bundle_size(m) for m in self._catalog.values()
            )
            quarantined = dict(self._quarantine)
            out = {
                "store": self.store.describe(),
                "unreachable": self.unreachable,
                "last_error": self.last_error,
                "bundles": bundles,
                "bundle_bytes": bundle_bytes,
                "quarantined": len(quarantined),
                "quarantined_keys": sorted(quarantined)[:8],
                "earliest_ms": None,
                "latest_ms": None,
                **{k: v for k, v in self.counters.items()},
            }
        out["earliest_ms"] = self.status_earliest_ms()
        out["latest_ms"] = self.latest_ms()
        comp = self.compactor
        if comp is not None:
            out["compactor"] = comp.status()
        return out

    @property
    def quarantined_count(self) -> int:
        """Lock-free quarantine count (len() on a dict is atomic under
        the GIL) — /healthz reads this without touching ``_lock``."""
        return len(self._quarantine)

    def status_earliest_ms(self) -> "int | None":
        """Oldest archived stamp across every tier."""
        return min(
            (e for e in (
                self.earliest_ms(0),
                self.earliest_ms(60_000),
                self.earliest_ms(600_000),
            ) if e is not None),
            default=None,
        )

    def close(self) -> None:
        self.store.close()


def _marker_key(bundle_key: str) -> str:
    return QUARANTINE_PREFIX + os.path.basename(bundle_key) + ".marker"


def _bundle_size(manifest: dict) -> int:
    """Section bytes a manifest indexes (observability sizing; the
    manifest frame + footer add a small constant on top)."""
    return sum(int(s.get("len", 0)) for s in manifest.get("sections", ()))


def _cache_file_ok(path: str, manifest: dict) -> bool:
    """Cheap cache-hit validation: the file's footer must point its
    manifest exactly past the section bytes this manifest indexes.
    (Full digest ran at download; per-section CRCs run at parse — a
    bit-rotted cache file fails there and re-downloads.)"""
    body = _bundle_size(manifest)
    try:
        size = os.path.getsize(path)
        if size < body + _FOOTER.size:
            return False
        with open(path, "rb") as f:
            f.seek(size - _FOOTER.size)
            tail = f.read(_FOOTER.size)
        if len(tail) != _FOOTER.size:
            return False
        moff, magic = _FOOTER.unpack(tail)
        return magic == _FOOTER_MAGIC and moff == body
    except OSError:
        return False
