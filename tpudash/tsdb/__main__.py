"""tsdb CLI: the crash drill, backup/restore, follower tailing, stats.

``python -m tpudash.tsdb drill --dir D [--kills N]``
    The durability claim, exercised for real: a child process appends
    frames to a store at ``D`` and seals continuously; the parent
    SIGKILLs it at a random moment mid-write, reopens the store, and
    asserts (1) the store loads cleanly (torn tails truncated, not
    fatal), (2) every block sealed before the kill is still readable,
    (3) the recovered point count never regresses below the previous
    iteration's sealed count.  Repeats ``--kills`` times.  Exit 0 =
    every recovery held; nonzero prints what was lost.  CI's chaos-soak
    job runs this on every PR.

``python -m tpudash.tsdb snapshot --dir D [--out ROOT]``
    One online snapshot of the store at ``D``: seals the head, hardlinks
    a consistent segment set + CRC-framed manifest into a timestamped
    directory under ROOT (default ``<D>/snapshots``), then runs
    retention-aware GC (``--keep``/``--retention``).  Safe against a
    live writer — sizes are captured under the store's segment-I/O
    lock, so every captured file ends on a record boundary.

``python -m tpudash.tsdb restore --snapshot S --dir DEST``
    Validate snapshot ``S`` (manifest frame CRC, every listed segment
    present/complete/CRC-matching) and copy it into the EMPTY directory
    ``DEST``.  Refuses torn or mismatched sets outright — exit 1 names
    the first mismatch; there is no partial-restore state.

``python -m tpudash.tsdb follow --leader L [--seconds N]``
    Tail ``L`` read-only as a hot standby for N seconds (0 = one poll),
    printing replication stats per poll — the smoke surface for
    follower mode (``TPUDASH_TSDB_FOLLOW`` serves a whole dashboard
    from the same machinery).

``python -m tpudash.tsdb stats --dir D``
    One JSON line of :meth:`TSDB.stats` for a store directory
    (read-only: never truncates another process's torn tail).

``python -m tpudash.tsdb compact --dir D --store SPEC [--cache C]``
    One compaction sweep: fold sealed segment files from ``D`` into
    digest-verified archive bundles at the object store ``SPEC`` (a
    directory path or ``file://`` URL), upload-then-verify-then-register
    (tpudash/tsdb/compact.py), and print the sweep summary as JSON.
    Safe against a live writer (reads sealed files only; the append
    target is skipped) and idempotent — deterministic bundle names make
    a re-run after a crash a no-op.  ``--include-tail`` also folds the
    current append target (final drain of a decommissioned store).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import random
import signal
import subprocess
import sys
import time

#: the child: open the store, append 8-chip frames at full speed with a
#: tiny chunk so seals (and segment appends) happen constantly — the
#: parent's SIGKILL then lands mid-write with high probability
_CHILD = """
import sys, time, numpy as np
from tpudash.tsdb import TSDB, FLEET_SERIES
store = TSDB(path=sys.argv[1], chunk_points=8)
keys = [f"slice-0/{i}" for i in range(8)] + [FLEET_SERIES]
cols = ["tensorcore_utilization", "hbm_usage_ratio", "power_watts"]
ts = time.time() - 1800.0  # recent stamps: retention must not eat them
i = 0
while True:
    mat = np.full((len(keys), len(cols)), float(i % 97), dtype=np.float32)
    store.append_frame(ts + i * 5.0, keys, cols, mat)
    store.flush()  # force the seal (and the segment write) inline
    i += 1
"""


def _sealed_points(path: str) -> int:
    from tpudash.tsdb import TSDB

    store = TSDB(path=path)
    return store.stats()["raw_points"]


def run_drill(dirpath: str, kills: int, seed: int) -> int:
    rng = random.Random(seed)
    os.makedirs(dirpath, exist_ok=True)
    prev_points = 0
    for round_no in range(1, kills + 1):
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD, dirpath],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        # let it import + seal for a bit, then kill mid-flight
        time.sleep(2.0 + rng.random() * 1.5)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        try:
            points = _sealed_points(dirpath)
        except Exception as e:  # noqa: BLE001 — a failed load IS the failure
            print(
                f"FAIL round {round_no}: store did not recover: {e}",
                file=sys.stderr,
            )
            return 1
        if points < prev_points:
            print(
                f"FAIL round {round_no}: sealed data lost "
                f"({prev_points} -> {points} points)",
                file=sys.stderr,
            )
            return 1
        print(
            f"round {round_no}/{kills}: kill -9 mid-append -> recovered "
            f"{points} sealed points (was {prev_points}); torn tail "
            "truncated cleanly"
        )
        prev_points = points
    if prev_points == 0:
        print("FAIL: no round ever sealed data — drill too short?",
              file=sys.stderr)
        return 1
    print(json.dumps({"drill": "ok", "kills": kills,
                      "recovered_points": prev_points}))
    return 0


def run_snapshot(dirpath: str, out: str, keep: int, retention: float) -> int:
    from tpudash.tsdb import TSDB
    from tpudash.tsdb.snapshot import SnapshotError, take_snapshot

    store = TSDB(
        path=dirpath,
        read_only=False,
        snapshot_keep=keep,
        snapshot_retention_s=retention,
    )
    try:
        result = take_snapshot(store, out or os.path.join(dirpath, "snapshots"))
    except SnapshotError as e:
        print(f"snapshot failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(result))
    return 0


def run_restore(snap: str, dest: str) -> int:
    from tpudash.tsdb import TSDB
    from tpudash.tsdb.snapshot import SnapshotError, restore_snapshot

    try:
        result = restore_snapshot(snap, dest)
    except SnapshotError as e:
        print(f"restore refused: {e}", file=sys.stderr)
        return 1
    # prove the restored set actually loads before declaring victory
    result["stats"] = TSDB(path=dest, read_only=True).stats()
    print(json.dumps(result))
    return 0


def run_follow(leader: str, seconds: float, interval: float) -> int:
    from tpudash.tsdb.follower import FollowerTSDB

    follower = FollowerTSDB(leader, poll_interval_s=interval)
    print(json.dumps(follower.replication))
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        time.sleep(interval)
        print(json.dumps(follower.poll()))
    print(json.dumps(follower.stats()))
    return 0


def run_compact(dirpath: str, spec: str, cache: str, min_age: float,
                bundle_mb: int, deadline: float, include_tail: bool) -> int:
    from tpudash.tsdb.cold import ColdTier
    from tpudash.tsdb.compact import Compactor
    from tpudash.tsdb.objstore import open_store

    try:
        store = open_store(spec)
    except ValueError as e:
        print(f"compact refused: {e}", file=sys.stderr)
        return 1
    cold = ColdTier(store, cache_dir=cache or os.path.join(dirpath, "cold-cache"))
    comp = Compactor(
        source_dir=dirpath,
        cold=cold,
        min_age_s=min_age,
        max_bundle_bytes=bundle_mb << 20,
        upload_deadline_s=deadline,
        include_tail=include_tail,
    )
    try:
        summary = comp.run_once()
    finally:
        with contextlib.suppress(OSError):
            comp.close()
        with contextlib.suppress(OSError):
            cold.close()
    print(json.dumps(summary))
    return 0 if not summary.get("gave_up") else 1


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpudash.tsdb")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("drill", help="kill -9 mid segment-append drill")
    d.add_argument("--dir", required=True)
    d.add_argument("--kills", type=int, default=3)
    d.add_argument("--seed", type=int, default=42)
    sn = sub.add_parser("snapshot", help="online snapshot of a live store")
    sn.add_argument("--dir", required=True)
    sn.add_argument("--out", default="", help="snapshot root "
                    "(default <dir>/snapshots)")
    sn.add_argument("--keep", type=int, default=5)
    sn.add_argument("--retention", type=float, default=0.0,
                    help="drop complete snapshots older than this many "
                    "seconds (0 = count-based GC only)")
    rs = sub.add_parser("restore", help="validated restore into an empty dir")
    rs.add_argument("--snapshot", required=True)
    rs.add_argument("--dir", required=True)
    fo = sub.add_parser("follow", help="tail a leader dir as a hot standby")
    fo.add_argument("--leader", required=True)
    fo.add_argument("--seconds", type=float, default=0.0)
    fo.add_argument("--interval", type=float, default=1.0)
    s = sub.add_parser("stats", help="dump a store's stats as JSON")
    s.add_argument("--dir", required=True)
    co = sub.add_parser("compact", help="one cold-tier compaction sweep")
    co.add_argument("--dir", required=True, help="segment directory to fold")
    co.add_argument("--store", required=True,
                    help="object-store spec (path or file:// URL)")
    co.add_argument("--cache", default="",
                    help="bundle cache dir (default <dir>/cold-cache)")
    co.add_argument("--min-age", type=float, default=0.0)
    co.add_argument("--bundle-mb", type=int, default=64)
    co.add_argument("--deadline", type=float, default=120.0)
    co.add_argument("--include-tail", action="store_true",
                    help="also fold the current append target (final "
                    "drain of a decommissioned store)")
    args = ap.parse_args(argv)
    if args.cmd == "drill":
        return run_drill(args.dir, args.kills, args.seed)
    if args.cmd == "snapshot":
        return run_snapshot(args.dir, args.out, args.keep, args.retention)
    if args.cmd == "restore":
        return run_restore(args.snapshot, args.dir)
    if args.cmd == "follow":
        return run_follow(args.leader, args.seconds, args.interval)
    if args.cmd == "compact":
        return run_compact(args.dir, args.store, args.cache, args.min_age,
                           args.bundle_mb, args.deadline, args.include_tail)
    from tpudash.tsdb import TSDB

    print(json.dumps(TSDB(path=args.dir, read_only=True).stats()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
