"""The embedded time-series store: head → sealed chunks → segment files.

Replaces the deque+whole-snapshot history tier with a real storage
engine, dependency-free:

- **Ingest** is frame-shaped, matching how the dashboard actually
  produces data: one ``append_frame(ts, keys, cols, matrix)`` per
  refresh (per-chip rows plus the ``FLEET_SERIES`` pseudo-row).  The
  mutable *head* keeps the raw (ts, matrix) pairs.
- **Sealing**: every ``chunk_points`` frames the head's oldest chunk is
  compressed into an immutable :class:`SealedBlock` — ONE Gorilla
  timestamp stream shared by every series of the frame, one XOR value
  stream per series (tpudash/tsdb/gorilla.py) — plus its 1m/10m rollup
  shadows (tpudash/tsdb/rollup.py).  Encoding runs on a daemon thread,
  never on the publish path; the chunk stays query-visible throughout
  (head → pending → sealed, no gap).
- **Persistence** (``path`` set): sealed blocks append to per-tier
  segment files as CRC-framed records.  A crash mid-append can tear at
  most the record being written: load verifies frame magic + CRC
  sequentially and truncates the torn tail, so *sealed* data already on
  disk is never lost — the drill (``python -m tpudash.tsdb drill``) and
  tests/test_tsdb.py kill -9 mid-write and assert exactly that.  The
  in-memory head is the only loss window (≤ ``chunk_points`` frames;
  ``close()`` seals it on a graceful shutdown).
- **Retention** is tiered (raw < 1m < 10m): expired blocks drop from
  memory per tier, and a segment file is deleted once every record in
  it expired — append-only files, whole-file reclaim, no rewrite.

Thread contract: ``_lock`` guards the in-memory structures and is held
only for pointer swaps (never I/O, never encoding); ``_io_lock`` is a
dedicated segment-file lock (the ``save_history`` pattern).  Callers on
the event loop must use an executor; everything here is sync on purpose.

Failure posture: disk trouble (full volume, yanked mount, corrupt
segment) degrades the store to memory-only with ``last_disk_error``
surfaced via :meth:`stats` — ingest and queries keep working, the
dashboard never crashes over its history tier (runbook:
docs/OPERATIONS.md).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import threading
import time
import zlib

import numpy as np

from tpudash import wireids
from tpudash.tsdb import gorilla
from tpudash.tsdb.rollup import (
    ALL_KEY,
    TIER_1M_MS,
    TIER_10M_MS,
    TIERS_MS,
    RollupBlock,
    SketchBlock,
    rollup_points,
    sketch_points,
)

log = logging.getLogger(__name__)

#: pseudo chip key carrying the fleet-average row in every frame matrix
#: ("/" makes it impossible as a real ``slice/chip`` key's collision —
#: real keys never start with "__")
FLEET_SERIES = "__fleet__"

_MAGIC = wireids.TSB1_MAGIC
_REC_BLOCK = wireids.TSB1_REC_BLOCK
_REC_ROLLUP = wireids.TSB1_REC_ROLLUP
#: PR-13 record type: quantile-sketch shadows beside the rollup quads.
#: Pre-13 readers walk past unknown record types (their loader only
#: dispatches on 1/2 and advances by the framed length), so a segment
#: directory stays readable in BOTH directions across the upgrade; a
#: new reader meeting a pre-13 directory backfills sketches from raw on
#: its first seal instead of refusing (see _maybe_backfill_sketches).
#: 4, not 3: snapshot.py already spent 3 on its MANIFEST record inside
#: the shared TSB1 framing — record types stay globally unique so any
#: tool can dispatch on type alone, whichever file it is reading.
_REC_SKETCH = wireids.TSB1_REC_SKETCH
_FRAME_HDR = struct.Struct("<4sBII")  # magic, type, payload len, crc32

#: segment rotation threshold — whole files are the retention unit, so
#: they must stay small enough that deleting one reclaims promptly
_SEG_MAX_BYTES = 4 << 20

_TIER_NAMES = {0: "raw", TIER_1M_MS: "1m", TIER_10M_MS: "10m"}


class SealedBlock:
    """One immutable compressed chunk: ``count`` frames over ``keys`` ×
    ``cols``.  ``ts_enc`` is the shared timestamp stream; ``val_enc[i]``
    is the value stream for series ``i = ki * len(cols) + ci``."""

    __slots__ = ("keys", "cols", "t0", "t1", "count", "ts_enc", "val_enc",
                 "_key_pos", "_ts_cache")

    def __init__(self, keys, cols, t0, t1, count, ts_enc, val_enc):
        self.keys = list(keys)
        self.cols = list(cols)
        self.t0 = int(t0)
        self.t1 = int(t1)
        self.count = int(count)
        self.ts_enc = ts_enc
        self.val_enc = val_enc
        self._key_pos = None
        self._ts_cache = None

    def nbytes(self) -> int:
        return len(self.ts_enc) + sum(len(v) for v in self.val_enc)

    def timestamps(self) -> "list[int]":
        if self._ts_cache is None:
            self._ts_cache = gorilla.decode_timestamps(self.ts_enc, self.count)
        return self._ts_cache

    def series_points(self, key: str, col: str):
        """(ts_ms list, float list) for one series, or None when this
        block never carried it (the chip was absent in this window)."""
        if self._key_pos is None:
            self._key_pos = {k: i for i, k in enumerate(self.keys)}
        ki = self._key_pos.get(key)
        if ki is None or col not in self.cols:
            return None
        ci = self.cols.index(col)
        vals = gorilla.decode_values(
            self.val_enc[ki * len(self.cols) + ci], self.count
        )
        return self.timestamps(), vals


def _encode_block(keys, cols, ts_ms, stacked) -> SealedBlock:
    """Compress one head chunk (encoding only — no locks, no I/O).
    ``stacked`` is the (n, K, C) float64 stack of the chunk's matrices."""
    n, K, C = stacked.shape
    flat = np.ascontiguousarray(stacked.reshape(n, K * C))
    ts_enc = gorilla.encode_timestamps(ts_ms)
    # Fortran order: each series' column becomes contiguous ONCE, so the
    # native encoder reads flat[:, i] without a per-series copy/tolist
    series_major = np.asfortranarray(flat)
    val_enc = [
        gorilla.encode_values(series_major[:, i]) for i in range(K * C)
    ]
    return SealedBlock(
        keys, cols, min(ts_ms), max(ts_ms), n, ts_enc, val_enc
    )


def _block_payload(b: SealedBlock) -> bytes:
    header = json.dumps(
        {
            "k": b.keys,
            "c": b.cols,
            "t0": b.t0,
            "t1": b.t1,
            "n": b.count,
            "tl": len(b.ts_enc),
            "vl": [len(v) for v in b.val_enc],
        },
        separators=(",", ":"),
    ).encode()
    return (
        struct.pack("<I", len(header))
        + header
        + b.ts_enc
        + b"".join(b.val_enc)
    )


def _record_header(payload: bytes) -> "tuple[dict, int]":
    """(header dict, body offset) of one segment-record payload.  The
    payload is untrusted (disk bit-rot, follower replication): a header
    that is not a JSON object refuses as ValueError here so the typed
    parsers below can subscript it."""
    (hlen,) = struct.unpack_from("<I", payload, 0)
    header = json.loads(payload[4 : 4 + hlen])
    if not isinstance(header, dict):
        raise ValueError("segment record header is not an object")
    return header, 4 + hlen


def _parse_block(payload: bytes) -> SealedBlock:
    header, off = _record_header(payload)
    try:
        tl = int(header["tl"])
        vls = [int(v) for v in header["vl"]]
        t0, t1 = int(header["t0"]), int(header["t1"])
        count = int(header["n"])
        keys, cols = list(header["k"]), list(header["c"])
    except (TypeError, ValueError) as e:
        # contract: a malformed record is ValueError/KeyError — a
        # wrong-typed header field must not escape as TypeError
        raise ValueError(f"malformed block header: {e!r}") from e
    ts_enc = payload[off : off + tl]
    off += tl
    val_enc = []
    for vl in vls:
        val_enc.append(payload[off : off + vl])
        off += vl
    return SealedBlock(keys, cols, t0, t1, count, ts_enc, val_enc)


def _rollup_payload(r: RollupBlock) -> bytes:
    header = json.dumps(
        {
            "tier": r.tier_ms,
            "k": r.keys,
            "c": r.cols,
            "nb": int(len(r.buckets)),
            "s0": r.src_t0,
            "s1": r.src_t1,
        },
        separators=(",", ":"),
    ).encode()
    return (
        struct.pack("<I", len(header))
        + header
        + np.ascontiguousarray(r.buckets, dtype=np.int64).tobytes()
        + np.ascontiguousarray(r.mn, dtype=np.float32).tobytes()
        + np.ascontiguousarray(r.mx, dtype=np.float32).tobytes()
        + np.ascontiguousarray(r.sm, dtype=np.float64).tobytes()
        + np.ascontiguousarray(r.cnt, dtype=np.int32).tobytes()
    )


def _parse_rollup(payload: bytes) -> RollupBlock:
    header, off = _record_header(payload)
    try:
        nb = int(header["nb"])
        K, C = len(header["k"]), len(header["c"])
        tier = int(header["tier"])
        s0, s1 = int(header["s0"]), int(header["s1"])
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed rollup header: {e!r}") from e
    if nb < 0:
        # np.frombuffer treats a negative count as "all remaining"
        raise ValueError("rollup bucket count negative")
    shape = (nb, K, C)

    def take(dtype, count):
        nonlocal off
        raw = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
        off += raw.nbytes
        return raw

    buckets = take(np.int64, nb)
    mn = take(np.float32, nb * K * C).reshape(shape)
    mx = take(np.float32, nb * K * C).reshape(shape)
    sm = take(np.float64, nb * K * C).reshape(shape)
    cnt = take(np.int32, nb * K * C).reshape(shape)
    return RollupBlock(
        tier, buckets, header["k"], header["c"], mn, mx, sm, cnt, s0, s1
    )


def _sketch_payload(s: SketchBlock) -> bytes:
    """Serialize one SketchBlock: JSON header (tier/keys/cols/bucket
    count/src bounds + the per-cell digest lengths, 0 = no digest) then
    the digests concatenated bucket-major.  Deterministic — the same
    block always produces the same bytes (the byte-stability the
    restart/replication tests pin rides on this)."""
    lens: "list[int]" = []
    blobs: "list[bytes]" = []
    for per_bucket in s.enc:
        for cells in per_bucket:
            for e in cells:
                if e:
                    lens.append(len(e))
                    blobs.append(e)
                else:
                    lens.append(0)
    header = json.dumps(
        {
            "tier": s.tier_ms,
            "k": s.keys,
            "c": s.cols,
            "nb": int(len(s.buckets)),
            "s0": s.src_t0,
            "s1": s.src_t1,
            "sl": lens,
        },
        separators=(",", ":"),
    ).encode()
    return (
        struct.pack("<I", len(header))
        + header
        + np.ascontiguousarray(s.buckets, dtype=np.int64).tobytes()
        + b"".join(blobs)
    )


def _parse_sketch(payload: bytes) -> SketchBlock:
    header, off = _record_header(payload)
    try:
        nb = int(header["nb"])
        keys, cols = header["k"], header["c"]
        K, C = len(keys), len(cols)
        lens = [int(x) for x in header["sl"]]
        tier = int(header["tier"])
        s0, s1 = int(header["s0"]), int(header["s1"])
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed sketch header: {e!r}") from e
    if nb < 0:
        raise ValueError("sketch bucket count negative")
    buckets = np.frombuffer(payload, dtype=np.int64, count=nb, offset=off)
    off += buckets.nbytes
    if len(lens) != nb * K * C:
        raise ValueError("sketch record cell count disagrees with header")
    enc: list = []
    i = 0
    for _b in range(nb):
        per_bucket: list = []
        for _k in range(K):
            cells: list = []
            for _c in range(C):
                ln = lens[i]
                i += 1
                if ln <= 0:
                    cells.append(None)
                else:
                    cells.append(payload[off : off + ln])
                    off += ln
            per_bucket.append(cells)
        enc.append(per_bucket)
    return SketchBlock(tier, buckets, keys, cols, enc, s0, s1)


class TSDB:
    def __init__(
        self,
        path: str = "",
        chunk_points: int = 120,
        retention_raw_s: float = 86400.0,
        retention_1m_s: float = 7 * 86400.0,
        retention_10m_s: float = 30 * 86400.0,
        flush_interval_s: float = 0.0,
        read_only: bool = False,
        snapshot_dir: str = "",
        snapshot_interval_s: float = 0.0,
        snapshot_keep: int = 5,
        snapshot_retention_s: float = 0.0,
        sketch_budget: int = 64,
        sketch_series: str = "10m",
        cold=None,
    ) -> None:
        self.path = path
        #: quantile-sketch rollups (tpudash.analytics.sketch): centroid
        #: budget per digest (0 disables sketching — quantile queries
        #: then degrade to raw folds / quad pseudo-digests), and which
        #: tiers keep PER-SERIES digests beside the fleet-distribution
        #: one: "10m" (default — the cheap tier), "all", or "fleet"
        #: (cross-series digests only)
        self.sketch_budget = max(0, int(sketch_budget))
        self.sketch_series = (
            sketch_series if sketch_series in ("10m", "all", "fleet") else "10m"
        )
        #: recording-rule engine (tpudash.analytics.rules), set by the
        #: service after construction; evaluated on the seal thread per
        #: sealed data chunk, outputs appended as first-class
        #: ``__rule__/`` series blocks
        self.rule_engine = None
        #: set when _load met raw blocks the sketch shadow doesn't cover
        #: (a pre-13 directory): the first seal drain backfills them
        self._sketch_backfill = False
        #: read-only mode: serve queries over an existing segment set
        #: (another instance's directory, or a snapshot) without ever
        #: appending, persisting, truncating, or reclaiming — the
        #: follower (tpudash/tsdb/follower.py) and the inspection CLI
        #: ride this; a live leader's files are never mutated
        self.read_only = bool(read_only)
        self.chunk_points = max(2, int(chunk_points))
        #: online-snapshot knobs (tpudash/tsdb/snapshot.py): with a dir
        #: and an interval set, the seal thread snapshots right after a
        #: chunk lands on disk — the ingest path never pauses for it
        self.snapshot_dir = snapshot_dir
        self.snapshot_interval_ms = int(max(0.0, snapshot_interval_s) * 1000)
        self.snapshot_keep = max(1, int(snapshot_keep))
        self.snapshot_retention_s = max(0.0, float(snapshot_retention_s))
        self._last_snapshot_mono: "float | None" = None
        self.last_snapshot: "dict | None" = None
        self.last_snapshot_error: "str | None" = None
        self.snapshots_taken = 0
        #: seal a partial head after this long anyway (0 = off) — bounds
        #: the crash-loss window in wall time on slow cadences
        self.flush_interval_ms = int(max(0.0, flush_interval_s) * 1000)
        self.retention_ms = {
            0: int(retention_raw_s * 1000),
            TIER_1M_MS: int(retention_1m_s * 1000),
            TIER_10M_MS: int(retention_10m_s * 1000),
        }
        #: set under synthetic load (profile replays must not pollute
        #: the persistent history)
        self.paused = False
        #: bumped on every visible mutation — query-result cache key
        self.version = 0
        self.last_disk_error: "str | None" = None
        self._lock = threading.RLock()
        #: dedicated segment-file lock (save_history pattern): disk I/O
        #: serializes here, never under the in-memory lock
        self._io_lock = threading.Lock()
        #: serializes the drain loop itself: flush() racing the seal
        #: thread must not encode (and double-commit) the same chunk
        self._seal_gate = threading.Lock()
        # head: mutable, query-visible, lost on crash (by contract)
        self._head_keys: list = []
        self._head_cols: list = []
        self._head_ts: "list[int]" = []
        self._head_mats: list = []
        #: chunks cut from the head, awaiting the encode thread — still
        #: query-visible in raw form
        self._pending: list = []  # [(keys, cols, ts_list, mats)]
        self._seal_thread: "threading.Thread | None" = None
        self._raw: "list[SealedBlock]" = []
        self._rollups = {t: [] for t in TIERS_MS}
        self._sketches = {t: [] for t in TIERS_MS}
        # per-tier segment registries: [(seq, path, newest_t1_ms)]
        self._segs = {name: [] for name in _TIER_NAMES.values()}
        #: cold tier (tpudash/tsdb/cold.py), attached via attach_cold:
        #: queries fold archive bundles in behind hot coverage, and the
        #: retention pass refuses to reclaim segments the cold tier has
        #: not verified into a bundle
        self.cold = None
        self._closed = False
        if cold is not None:
            # attached BEFORE the load-time retention pass: segments
            # that expired while the process was down must face the
            # reclaim gate too — attach_cold() after construction
            # would leave a window where nothing vouches for them
            self.attach_cold(cold)
        if path:
            self._load()

    def attach_cold(self, cold) -> None:
        """Wire a :class:`~tpudash.tsdb.cold.ColdTier` behind this
        store.  Catalog changes bump ``version`` so range-result caches
        (the server ETag) see newly archived history."""
        cold.on_change = self._bump_version
        self.cold = cold
        self._bump_version()

    def _bump_version(self) -> None:
        with self._lock:
            self.version += 1

    @classmethod
    def from_config(cls, cfg, cold=None) -> "TSDB":
        return cls(
            path=cfg.tsdb_path,
            cold=cold,
            chunk_points=cfg.tsdb_chunk_points,
            retention_raw_s=cfg.tsdb_retention_raw,
            retention_1m_s=cfg.tsdb_retention_1m,
            retention_10m_s=cfg.tsdb_retention_10m,
            flush_interval_s=cfg.tsdb_flush_interval,
            snapshot_dir=cfg.tsdb_snapshot_dir,
            snapshot_interval_s=cfg.tsdb_snapshot_interval,
            snapshot_keep=cfg.tsdb_snapshot_keep,
            snapshot_retention_s=cfg.tsdb_snapshot_retention,
            sketch_budget=getattr(cfg, "sketch_budget", 64),
            sketch_series=getattr(cfg, "sketch_series", "10m"),
        )

    # -- ingest --------------------------------------------------------------
    def append_frame(self, ts_s: float, keys, cols, matrix) -> None:
        """One refresh's worth of samples: ``matrix[k, c]`` is the value
        of series (keys[k], cols[c]) at ``ts_s`` (NaN = no sample).  A
        population change (chip churn, new metric) seals the current
        head with ITS alignment and starts a fresh one — old blocks keep
        serving the departed chip's history."""
        if self.paused or self._closed or self.read_only:
            return
        ts_ms = gorilla.ts_to_ms(ts_s)
        mat = np.asarray(matrix, dtype=np.float32)
        keys = list(keys)
        cols = list(cols)
        kick = False
        with self._lock:
            if self._head_ts and (
                keys != self._head_keys or cols != self._head_cols
            ):
                self._cut_head_locked()
                kick = True
            self._head_keys = keys
            self._head_cols = cols
            self._head_ts.append(ts_ms)
            self._head_mats.append(mat)
            if len(self._head_ts) >= self.chunk_points or (
                self.flush_interval_ms
                and ts_ms - self._head_ts[0] >= self.flush_interval_ms
            ):
                self._cut_head_locked()
                kick = True
            self.version += 1
        if kick:
            self._kick_seal()

    def _cut_head_locked(self) -> None:
        if not self._head_ts:
            return
        self._pending.append(
            (self._head_keys, self._head_cols, self._head_ts, self._head_mats)
        )
        self._head_ts = []
        self._head_mats = []

    def _kick_seal(self) -> None:
        with self._lock:
            if self._seal_thread is not None and self._seal_thread.is_alive():
                return
            t = threading.Thread(
                target=self._seal_pending, name="tsdb-seal", daemon=True
            )
            self._seal_thread = t
        t.start()

    def _seal_pending(self) -> None:
        """Drain pending chunks: encode (no locks), commit (in-memory
        lock), persist (I/O lock), retain.  Runs on the seal thread, or
        inline via flush(); the gate keeps the two from double-sealing
        one chunk.  Encoding and disk writes happen through method
        calls, so nothing blocking sits lexically under the gate."""
        with self._seal_gate:
            self._maybe_backfill_sketches()
            while True:
                with self._lock:
                    if not self._pending:
                        # deregister BEFORE returning (under the lock):
                        # a _kick_seal racing this thread's death would
                        # otherwise see is_alive() == True, spawn
                        # nothing, and strand a freshly cut chunk in
                        # _pending until the NEXT cut — a crash in that
                        # window would lose sealed-cut data the
                        # durability contract promises to keep
                        if self._seal_thread is threading.current_thread():
                            self._seal_thread = None
                        return
                    keys, cols, ts_list, mats = self._pending[0]
                stacked = np.stack(mats).astype(np.float64)
                block = _encode_block(keys, cols, ts_list, stacked)
                rolls, sketches = self._shadow_blocks(
                    ts_list, keys, cols, stacked
                )
                # recording rules (tpudash.analytics.rules): derived
                # series for this chunk, sealed as first-class blocks of
                # their own — encoded, rolled up, sketched, persisted,
                # retained, replicated exactly like scraped data.  The
                # engine never raises (it degrades to last_error); the
                # float32 round-trip matches the append path so a rule
                # evaluated here and a rule value ever re-derived agree
                # byte-for-byte.
                derived = None
                eng = self.rule_engine
                if eng is not None:
                    derived = eng.evaluate(ts_list, keys, cols, stacked)
                with self._lock:
                    self._pending.pop(0)
                    self._raw.append(block)
                    for r in rolls:
                        self._rollups[r.tier_ms].append(r)
                    for s in sketches:
                        self._sketches[s.tier_ms].append(s)
                    self.version += 1
                if self.path and not self.read_only:
                    self._persist(block, rolls, sketches)
                if derived is not None:
                    self._seal_derived(ts_list, derived)
                self._enforce_retention()
                self._maybe_autosnapshot()

    def _per_series_tier(self, tier: int) -> bool:
        """Does this tier keep PER-SERIES sketches beside the fleet
        digest?  The one predicate seal, backfill, and the coverage
        check all share — desynchronizing them would re-trigger the
        one-shot backfill on every restart."""
        return self.sketch_series == "all" or (
            self.sketch_series == "10m" and tier == TIER_10M_MS
        )

    def _shadow_blocks(self, ts_list, keys, cols, stacked):
        """Rollup + sketch shadows for one chunk (encoding only — no
        locks, no I/O)."""
        rolls, sketches = [], []
        for tier in TIERS_MS:
            r = rollup_points(tier, ts_list, keys, cols, stacked)
            if r is not None:
                rolls.append(r)
            if self.sketch_budget > 0:
                s = sketch_points(
                    tier, ts_list, keys, cols, stacked,
                    self.sketch_budget, self._per_series_tier(tier),
                )
                if s is not None:
                    sketches.append(s)
        return rolls, sketches

    def _seal_derived(self, ts_list, derived) -> None:
        """Commit one chunk's recording-rule output as its own sealed
        block set.  Rule keys are ``__``-prefixed, so sketch_points
        keeps them out of the fleet-distribution digest; per-series
        digests still cover them on the configured tiers (a rule series
        is range-queryable with agg=p99 like any chip)."""
        dkeys, dcols, dstack = derived
        # float32 round-trip: the exact dtype path scraped frames take
        # through append_frame, so re-deriving a rule value can never
        # disagree with the sealed bytes over float64 tail digits
        dstack = np.asarray(dstack, dtype=np.float32).astype(np.float64)
        dblock = _encode_block(dkeys, dcols, ts_list, dstack)
        drolls, dsketches = self._shadow_blocks(ts_list, dkeys, dcols, dstack)
        with self._lock:
            self._raw.append(dblock)
            for r in drolls:
                self._rollups[r.tier_ms].append(r)
            for s in dsketches:
                self._sketches[s.tier_ms].append(s)
            self.version += 1
        if self.path and not self.read_only:
            self._persist(dblock, drolls, dsketches)

    def _sketch_possible(self, block_keys, tier: int) -> bool:
        """Can sketch_points produce ANY output for a block of these
        keys at this tier?  False for an all-pseudo-series block (e.g.
        a ``__rule__/``-only derived block) on a tier without
        per-series digests — such blocks must not count as "uncovered"
        or the one-shot backfill would re-trigger (and decode them for
        nothing) on every restart."""
        if self._per_series_tier(tier):
            return True
        return any(not str(k).startswith("__") for k in block_keys)

    def _maybe_backfill_sketches(self) -> None:
        """PR-13 upgrade path: a directory written before sketches
        existed loads with raw blocks the sketch shadow doesn't cover.
        Backfill them HERE — on the seal thread, once, from the raw
        points (exact digests, not quad approximations) — so quantile
        queries answer from sketches a drain later, and a pre-13
        directory is never refused and never permanently second-class.
        Raw that already expired can't be backfilled; those windows keep
        answering through the quad pseudo-digest fallback."""
        if not self._sketch_backfill or self.sketch_budget <= 0:
            return
        self._sketch_backfill = False
        with self._lock:
            blocks = list(self._raw)
            covered = {
                t: [(s.src_t0, s.src_t1) for s in self._sketches[t]]
                for t in TIERS_MS
            }
        made = 0
        for b in blocks:
            missing = [
                t for t in TIERS_MS
                if self._sketch_possible(b.keys, t)
                and not any(
                    lo <= b.t0 and b.t1 <= hi for lo, hi in covered[t]
                )
            ]
            if not missing:
                continue
            ts_list = b.timestamps()
            stacked = np.empty(
                (b.count, len(b.keys), len(b.cols)), dtype=np.float64
            )
            for ki in range(len(b.keys)):
                for ci in range(len(b.cols)):
                    stacked[:, ki, ci] = gorilla.decode_values(
                        b.val_enc[ki * len(b.cols) + ci], b.count
                    )
            news = []
            for tier in missing:
                s = sketch_points(
                    tier, ts_list, b.keys, b.cols, stacked,
                    self.sketch_budget, self._per_series_tier(tier),
                )
                if s is not None:
                    news.append(s)
            if not news:
                continue
            made += len(news)
            with self._lock:
                for s in news:
                    self._sketches[s.tier_ms].append(s)
                self.version += 1
            if self.path and not self.read_only:
                self._persist(None, [], news)
        if made:
            log.info(
                "tsdb backfilled %d sketch blocks from pre-sketch raw "
                "segments", made,
            )

    def flush(self, seal_partial: bool = False) -> None:
        """Synchronously seal everything pending (and, with
        ``seal_partial``, the not-yet-full head) — tests, migration,
        shutdown.  Joins any in-flight seal thread first."""
        t = self._seal_thread
        if t is not None and t.is_alive():
            t.join()
        if seal_partial:
            with self._lock:
                self._cut_head_locked()
        self._seal_pending()

    def close(self) -> None:
        """Graceful shutdown: seal the partial head so a clean restart
        loses nothing (a crash still loses only the head, by design)."""
        if self._closed:
            return
        self.flush(seal_partial=True)
        self._closed = True

    def _maybe_autosnapshot(self) -> None:
        """Interval-gated online snapshot, run at the tail of a seal
        drain (the snapshot module's ``cut_head=False`` path: the head
        was just cut, and re-entering the seal gate from here would
        deadlock).  Failures degrade to ``last_snapshot_error`` on
        stats() — a full snapshot volume must not take sealing down."""
        if (
            not self.snapshot_dir
            or not self.snapshot_interval_ms
            or not self.path
            or self.read_only
        ):
            return
        now = time.monotonic()
        if (
            self._last_snapshot_mono is not None
            and (now - self._last_snapshot_mono) * 1000
            < self.snapshot_interval_ms
        ):
            return
        self._last_snapshot_mono = now
        from tpudash.tsdb import snapshot as snapmod

        try:
            self.last_snapshot = snapmod.take_snapshot(
                self, self.snapshot_dir, cut_head=False
            )
            self.snapshots_taken += 1
            self.last_snapshot_error = None
        except snapmod.SnapshotError as e:
            if str(e) != self.last_snapshot_error:
                log.warning("tsdb auto-snapshot failed: %s", e)
            self.last_snapshot_error = str(e)

    # -- persistence ---------------------------------------------------------
    def _tier_name(self, tier_ms: int) -> str:
        return _TIER_NAMES[tier_ms]

    # tpulint: allow[blocking-under-lock] dedicated segment-I/O lock (save_history pattern), never the in-memory lock
    def _persist(self, block: "SealedBlock | None", rolls, sketches=()) -> None:
        with self._io_lock:
            try:
                if block is not None:
                    self._write_record(
                        "raw", _REC_BLOCK, _block_payload(block), block.t1
                    )
                for r in rolls:
                    self._write_record(
                        self._tier_name(r.tier_ms),
                        _REC_ROLLUP,
                        _rollup_payload(r),
                        r.t1,
                    )
                for s in sketches:
                    self._write_record(
                        self._tier_name(s.tier_ms),
                        _REC_SKETCH,
                        _sketch_payload(s),
                        s.t1,
                    )
                if self.last_disk_error is not None:
                    log.info("tsdb disk writes recovered")
                    self.last_disk_error = None
            except OSError as e:
                # disk full / yanked volume: degrade to memory-only,
                # surface on stats(), never take the dashboard down
                if str(e) != self.last_disk_error:
                    log.warning("tsdb segment write failed: %s", e)
                self.last_disk_error = str(e)

    def _write_record(
        self, tier: str, rec_type: int, payload: bytes, newest_t1: int
    ) -> None:
        """Append one CRC-framed record to the tier's current segment
        (caller holds _io_lock).  The whole frame goes down in one
        buffered write + flush; a crash can tear only this record — the
        loader's CRC walk drops the torn tail."""
        segs = self._segs[tier]
        if not segs or self._seg_size(segs[-1][1]) > _SEG_MAX_BYTES:
            seq = (segs[-1][0] + 1) if segs else 1
            segs.append(
                [seq, os.path.join(self.path, f"{tier}-{seq:06d}.seg"), 0]
            )
        entry = segs[-1]
        frame = _FRAME_HDR.pack(
            _MAGIC, rec_type, len(payload), zlib.crc32(payload)
        ) + payload
        os.makedirs(self.path, exist_ok=True)
        with open(entry[1], "ab") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        entry[2] = max(entry[2], newest_t1)

    @staticmethod
    def _seg_size(path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def _load(self) -> None:
        """Replay every segment record into memory.  Sequential CRC
        walk; the first bad frame in a file ends that file's content —
        in the newest file of a tier it is a torn tail from a crash
        mid-append, and the file is truncated back to the last good
        record so future appends stay parseable."""
        try:
            os.makedirs(self.path, exist_ok=True)
            names = sorted(os.listdir(self.path))
        except OSError as e:
            log.warning("tsdb open failed (%s): %s", self.path, e)
            self.last_disk_error = str(e)
            return
        for tier in self._segs:
            tier_files = [
                n
                for n in names
                if n.startswith(f"{tier}-") and n.endswith(".seg")
            ]
            for i, name in enumerate(tier_files):
                full = os.path.join(self.path, name)
                try:
                    seq = int(name[len(tier) + 1 : -4])
                except ValueError:
                    continue
                newest = self._load_segment(
                    full,
                    truncate_tail=(
                        i == len(tier_files) - 1 and not self.read_only
                    ),
                )
                self._segs[tier].append([seq, full, newest])
        self._enforce_retention()
        n_raw = len(self._raw)
        if n_raw:
            # pre-13 directory (or one written with sketches disabled):
            # raw survives that no sketch shadow covers — schedule the
            # one-shot backfill for the first seal drain
            if self.sketch_budget > 0 and not self.read_only:
                spans = {
                    t: [(s.src_t0, s.src_t1) for s in self._sketches[t]]
                    for t in TIERS_MS
                }
                self._sketch_backfill = any(
                    self._sketch_possible(b.keys, t)
                    and not any(
                        lo <= b.t0 and b.t1 <= hi for lo, hi in spans[t]
                    )
                    for b in self._raw
                    for t in TIERS_MS
                )
            log.info(
                "tsdb restored %d raw blocks (%d points) + %d rollup blocks "
                "+ %d sketch blocks from %s",
                n_raw,
                sum(b.count for b in self._raw),
                sum(len(v) for v in self._rollups.values()),
                sum(len(v) for v in self._sketches.values()),
                self.path,
            )

    def _load_segment(self, path: str, truncate_tail: bool) -> int:
        newest = 0
        good_end = 0
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            log.warning("tsdb segment unreadable (%s): %s", path, e)
            return 0
        off = 0
        while off + _FRAME_HDR.size <= len(data):
            magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(data, off)
            payload = data[off + _FRAME_HDR.size : off + _FRAME_HDR.size + plen]
            if (
                magic != _MAGIC
                or len(payload) != plen
                or zlib.crc32(payload) != crc
            ):
                break  # torn tail (crash mid-append) or corruption
            try:
                if rec_type == _REC_BLOCK:
                    b = _parse_block(payload)
                    self._raw.append(b)
                    newest = max(newest, b.t1)
                elif rec_type == _REC_ROLLUP:
                    r = _parse_rollup(payload)
                    if r.tier_ms in self._rollups:
                        self._rollups[r.tier_ms].append(r)
                        newest = max(newest, r.t1)
                elif rec_type == _REC_SKETCH:
                    s = _parse_sketch(payload)
                    if s.tier_ms in self._sketches:
                        self._sketches[s.tier_ms].append(s)
                        newest = max(newest, s.t1)
                # unknown record types from a NEWER writer: skip the
                # framed payload — same grace pre-13 readers extend us
            except (ValueError, KeyError, json.JSONDecodeError, struct.error):
                break  # CRC passed but the payload lies: stop trusting
            off += _FRAME_HDR.size + plen
            good_end = off
        if good_end < len(data):
            log.warning(
                "tsdb segment %s: torn/corrupt tail at byte %d of %d "
                "(sealed records before it are intact)",
                path,
                good_end,
                len(data),
            )
            if truncate_tail:
                with contextlib.suppress(OSError):
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
        return newest

    # -- retention -----------------------------------------------------------
    def _now_ms(self) -> int:
        # tpulint: allow[wall-clock] retention compares persisted epoch stamps
        return int(time.time() * 1000)

    def _enforce_retention(self) -> None:
        now = self._now_ms()
        with self._lock:
            cut_raw = now - self.retention_ms[0]
            self._raw = [b for b in self._raw if b.t1 >= cut_raw]
            for tier in TIERS_MS:
                cut = now - self.retention_ms[tier]
                self._rollups[tier] = [
                    r for r in self._rollups[tier] if r.t1 >= cut
                ]
                self._sketches[tier] = [
                    s for s in self._sketches[tier] if s.t1 >= cut
                ]
            self.version += 1
        self._reclaim_segments(now)

    # whole-file reclaim: a segment goes once its newest record expired
    # for its tier (the current append target is kept)
    def _reclaim_segments(self, now: int) -> None:
        if self.read_only:
            return  # never delete another instance's files
        with self._io_lock:  # tpulint: allow[blocking-under-lock] dedicated segment-I/O lock (save_history pattern), never the in-memory lock
            for tier, tier_ms in (("raw", 0), ("1m", TIER_1M_MS),
                                  ("10m", TIER_10M_MS)):
                cut = now - self.retention_ms[tier_ms]
                segs = self._segs[tier]
                keep = []
                for entry in segs:
                    expired = entry[2] > 0 and entry[2] < cut
                    if expired and entry is not segs[-1]:
                        if not self._cold_retire_ok(entry[1]):
                            # the cold tier has not verified this file
                            # into a bundle (store dark, compactor
                            # behind): PAUSE reclaim — retention never
                            # outranks durability
                            keep.append(entry)
                            continue
                        with contextlib.suppress(OSError):
                            os.remove(entry[1])
                        continue
                    keep.append(entry)
                self._segs[tier] = keep

    def _cold_retire_ok(self, path: str) -> bool:
        """May this expired segment file be deleted?  True when no cold
        tier is configured (pre-18 behaviour), or when a verified bundle
        covers the file's full current byte length."""
        cold = self.cold
        if cold is None:
            return True
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            return True  # already gone
        return cold.covers_segment(os.path.basename(path), nbytes)

    # -- queries -------------------------------------------------------------
    def raw_window(self, key: str, col: str, start_ms: int, end_ms: int):
        """All raw points of one series in [start_ms, end_ms], ts-sorted
        (sealed + pending + head — a chunk mid-seal is never invisible)."""
        pts: "list[tuple[int, float]]" = []
        with self._lock:
            blocks = [
                b for b in self._raw
                if b.t1 >= start_ms and b.t0 <= end_ms
            ]
            pending = list(self._pending)
            if self._head_ts:
                pending.append(
                    (self._head_keys, self._head_cols,
                     list(self._head_ts), list(self._head_mats))
                )
        for b in blocks:
            got = b.series_points(key, col)
            if got is None:
                continue
            ts_list, vals = got
            pts.extend(
                (t, v)
                for t, v in zip(ts_list, vals)
                if start_ms <= t <= end_ms
            )
        for keys, cols, ts_list, mats in pending:
            if key not in keys or col not in cols:
                continue
            ki = keys.index(key)
            ci = cols.index(col)
            pts.extend(
                (t, float(m[ki, ci]))
                for t, m in zip(ts_list, mats)
                if start_ms <= t <= end_ms
            )
        cold_end = self._cold_clamp(0, start_ms, end_ms)
        if cold_end is not None:
            pts.extend(
                self.cold.raw_points(key, col, start_ms, cold_end)
            )
        pts.sort(key=lambda p: p[0])
        return pts

    def _cold_clamp(self, tier_ms: int, start_ms: int,
                    end_ms: int) -> "int | None":
        """Right edge of the window the COLD tier should answer for, or
        None when cold has nothing to add.  Cold serves strictly before
        hot coverage of the tier — at the boundary the hot copy wins, so
        a record archived while still hot is never double-counted."""
        cold = self.cold
        if cold is None:
            return None
        hot_lo = self._hot_earliest_ms(tier_ms)
        cold_end = end_ms if hot_lo is None else min(end_ms, hot_lo - 1)
        return cold_end if cold_end >= start_ms else None

    def rollup_window(self, tier_ms: int, key: str, col: str,
                      start_ms: int, end_ms: int):
        """Merged (bucket_ms, mn, mx, sm, cnt) quads for one series in
        the window, including an on-the-fly fold of raw points newer
        than the sealed rollups (head/pending must not leave a visible
        gap at the right edge of a downsampled graph)."""
        from tpudash.tsdb.rollup import merge_quads

        quads = []
        with self._lock:
            blocks = [
                r for r in self._rollups.get(tier_ms, [])
                if r.src_t1 >= start_ms and r.src_t0 <= end_ms
            ]
        sealed_hi = 0
        for r in blocks:
            # a bucket belongs to the window when it INTERSECTS it —
            # data late in a bucket must not vanish because the bucket's
            # aligned start precedes the window
            quads.extend(
                q for q in r.series_quads(key, col)
                if q[0] + tier_ms - 1 >= start_ms and q[0] <= end_ms
            )
            sealed_hi = max(sealed_hi, r.src_t1)
        cold_end = self._cold_clamp(tier_ms, start_ms, end_ms)
        if cold_end is not None:
            cquads = self.cold.rollup_window(tier_ms, key, col,
                                             start_ms, cold_end)
            if cquads:
                quads.extend(cquads)
                # raw fold must start after the archived coverage, but
                # only as far as the archives actually reach — clamping
                # to cold_end here would silence the live head on a
                # store whose hot rollups haven't sealed yet
                cold_hi = max(q[0] + tier_ms - 1 for q in cquads)
                sealed_hi = max(sealed_hi, min(cold_end, cold_hi))
        live_from = max(start_ms, sealed_hi + 1)
        if live_from <= end_ms:
            for t, v in self.raw_window(key, col, live_from, end_ms):
                if v == v:  # NaN contributes nothing
                    quads.append((t // tier_ms * tier_ms, v, v, v, 1))
        return merge_quads(quads)

    def sketch_series_window(
        self,
        tier_ms: int,
        key: str,
        col: str,
        start_ms: int,
        end_ms: int,
        quads_by_key: "dict | None" = None,
    ):
        """Merged per-tier-bucket quantile digests for one series in
        the window: ``[(bucket_ms, QuantileSketch)]``, ascending.  The
        series may be a real chip, a ``__rule__/`` output, the
        ``__fleet__`` row, or :data:`ALL_KEY` (the fleet distribution).

        Coverage composes three layers, best first:

        1. sealed sketch records of the tier;
        2. buckets the sketches miss but raw still holds (a tier
           without per-series digests, a pre-13 directory awaiting
           backfill): EXACT digests folded from the raw points;
        3. buckets where raw expired too (old pre-13 rollups): the
           quad's 3-centroid pseudo-digest — coarse, but an answer,
           which is the "never refuse a pre-13 dir" contract;

        plus the live tail: raw samples NEWER than the sealed sketch
        coverage (head/pending, or a chunk sealed after the sketches'
        span) fold into their buckets even when a sealed digest already
        partially covers the bucket — the current bucket's p99 must see
        the newest samples exactly like rollup_window's mean does.

        ``tier_ms`` 0 folds raw at 1m granularity (fine-step queries).
        ``quads_by_key`` lets a caller that already ran
        ``rollup_window`` per key (the state executor's hot path) share
        that pass instead of paying it twice."""
        from tpudash.analytics.sketch import QuantileSketch, SketchError

        budget = self.sketch_budget or 64
        tier = tier_ms if tier_ms > 0 else TIER_1M_MS
        out: "dict[int, list]" = {}
        covered: set = set()
        sealed_hi = 0
        if tier_ms > 0:
            with self._lock:
                blocks = [
                    s for s in self._sketches.get(tier, [])
                    if s.src_t1 >= start_ms and s.src_t0 <= end_ms
                ]
            for blk in blocks:
                contributed = False
                for b, raw in blk.series(key, col):
                    if b + tier - 1 < start_ms or b > end_ms:
                        continue
                    try:
                        sk = QuantileSketch.from_bytes(raw, budget)
                    except SketchError:
                        continue  # one bad cell, not a dead query
                    out.setdefault(b, []).append(sk)
                    covered.add(b)
                    contributed = True
                if contributed:
                    sealed_hi = max(sealed_hi, blk.src_t1)
        if tier_ms > 0 and self.cold is not None:
            # archived sketch digests serve the window below hot sketch
            # coverage — same boundary discipline as the quad fold: the
            # hot copy wins, and sealed_hi only advances as far as the
            # archives actually reach
            with self._lock:
                sk_lo = min(
                    (s.src_t0 for s in self._sketches.get(tier, [])),
                    default=None,
                )
            cold_end = end_ms if sk_lo is None else min(end_ms, sk_lo - 1)
            if cold_end >= start_ms:
                digs, cold_hi = self.cold.sketch_digests(
                    tier, key, col, start_ms, cold_end
                )
                for b, raw in digs:
                    if b + tier - 1 < start_ms or b > end_ms:
                        continue
                    try:
                        sk = QuantileSketch.from_bytes(raw, budget)
                    except SketchError:
                        continue  # one bad archived cell, not a dead query
                    out.setdefault(b, []).append(sk)
                    covered.add(b)
                if digs:
                    sealed_hi = max(sealed_hi, min(cold_end, cold_hi))
        # rollup_window already folds the live raw tail into quads, so
        # it doubles as the "which buckets exist at all" oracle
        if key == ALL_KEY:
            keys = [
                k for k in sorted(self.series_keys())
                if not k.startswith("__")
            ]
        else:
            keys = [key]
        gaps: "dict[int, list]" = {}
        for k in keys:
            quads = (
                quads_by_key.get(k, ())
                if quads_by_key is not None
                else self.rollup_window(tier, k, col, start_ms, end_ms)
            )
            for bt, mn, mx, sm, cnt in quads:
                if cnt > 0 and bt not in covered:
                    gaps.setdefault(bt, []).append((mn, mx, sm, cnt))
        # live tail for COVERED buckets: samples newer than the sealed
        # sketch span merge in as an exact partial digest (no overlap —
        # the sealed digests end at sealed_hi by construction)
        tail_from = max(start_ms, sealed_hi + 1)
        tail_vals: "dict[int, list]" = {}
        if covered and tail_from <= end_ms:
            for k in keys:
                for t, v in self.raw_window(k, col, tail_from, end_ms):
                    if v == v:
                        b = t // tier * tier
                        if b in covered:
                            tail_vals.setdefault(b, []).append(v)
        if gaps:
            lo = max(min(gaps), start_ms)
            hi = min(max(gaps) + tier - 1, end_ms)
            vals: "dict[int, list]" = {}
            for k in keys:
                for t, v in self.raw_window(k, col, lo, hi):
                    if v == v:
                        b = t // tier * tier
                        if b in gaps:
                            vals.setdefault(b, []).append(v)
            for b, quads in gaps.items():
                got = vals.get(b)
                if got:
                    out.setdefault(b, []).append(
                        QuantileSketch.from_values(got, budget)
                    )
                else:
                    out.setdefault(b, []).extend(
                        QuantileSketch.from_quad(mn, mx, sm, cnt, budget)
                        for mn, mx, sm, cnt in quads
                    )
        for b, got in tail_vals.items():
            out.setdefault(b, []).append(
                QuantileSketch.from_values(got, budget)
            )
        return [
            (
                b,
                sks[0]
                if len(sks) == 1
                else QuantileSketch.merged(sks, budget),
            )
            for b, sks in sorted(out.items())
        ]

    def series_keys(self) -> "set[str]":
        """Every series key the store currently knows (any tier)."""
        out: set = set()
        with self._lock:
            for b in self._raw:
                out.update(b.keys)
            for blocks in self._rollups.values():
                for r in blocks:
                    out.update(r.keys)
            out.update(self._head_keys)
            for keys, _cols, _ts, _m in self._pending:
                out.update(keys)
        cold = self.cold
        if cold is not None:
            cold.refresh()
            out.update(cold.series_keys())
        out.discard(FLEET_SERIES)
        return out

    def series_cols(self, key: str) -> "list[str]":
        cols: dict = {}
        with self._lock:
            sources: list = [(b.keys, b.cols) for b in self._raw]
            sources += [(k, c) for k, c, _t, _m in self._pending]
            if self._head_ts:
                sources.append((self._head_keys, self._head_cols))
            for blocks in self._rollups.values():
                sources += [(r.keys, r.cols) for r in blocks]
        for keys, block_cols in sources:
            if key in keys:
                for c in block_cols:
                    cols[c] = None
        cold = self.cold
        if cold is not None and key in cold.series_keys():
            for c in cold.series_cols():
                cols.setdefault(c, None)
        return list(cols)

    def point_count(self, key: str) -> int:
        """Raw-tier point count for one series (horizon comparisons)."""
        n = 0
        with self._lock:
            for b in self._raw:
                if key in b.keys:
                    n += b.count
            for keys, _c, ts_list, _m in self._pending:
                if key in keys:
                    n += len(ts_list)
            if key in self._head_keys:
                n += len(self._head_ts)
        return n

    def _hot_earliest_ms(self, tier_ms: int = 0) -> "int | None":
        """Oldest HOT coverage for a tier — the boundary below which
        cold-tier reads take over (see :meth:`_cold_clamp`)."""
        with self._lock:
            if tier_ms == 0:
                t0s = [b.t0 for b in self._raw]
                t0s += [ts[0] for _k, _c, ts, _m in self._pending if ts]
                if self._head_ts:
                    t0s.append(self._head_ts[0])
            else:
                t0s = [r.src_t0 for r in self._rollups.get(tier_ms, [])]
        return min(t0s) if t0s else None

    def earliest_ms(self, tier_ms: int = 0) -> "int | None":
        lo = self._hot_earliest_ms(tier_ms)
        cold = self.cold
        if cold is not None:
            cold.refresh()
            c = cold.earliest_ms(tier_ms)
            if c is not None and (lo is None or c < lo):
                lo = c
        return lo

    def _hot_latest_ms(self) -> "int | None":
        with self._lock:
            t1s = [b.t1 for b in self._raw]
            t1s += [ts[-1] for _k, _c, ts, _m in self._pending if ts]
            if self._head_ts:
                t1s.append(self._head_ts[-1])
            for blocks in self._rollups.values():
                t1s += [r.t1 for r in blocks]
        return max(t1s) if t1s else None

    def latest_ms(self) -> "int | None":
        hi = self._hot_latest_ms()
        cold = self.cold
        if cold is not None:
            cold.refresh()
            c = cold.latest_ms()
            if c is not None and (hi is None or c > hi):
                hi = c
        return hi

    def stats(self) -> dict:
        """Observability snapshot (rides /api/timings)."""
        with self._lock:
            # recording-rule outputs are first-class blocks, but the
            # point counters keep their pre-13 meaning (scraped data):
            # migrations and tests reason about "did my frames survive",
            # and derived series would double-count them.  One pass —
            # this runs under the ingest lock on every /api/timings poll
            derived = []
            raw_pts = 0
            for b in self._raw:
                if b.keys and all(
                    k.startswith("__rule__/") for k in b.keys
                ):
                    derived.append(b)
                else:
                    raw_pts += b.count
            pend_pts = sum(len(ts) for _k, _c, ts, _m in self._pending)
            comp_bytes = sum(b.nbytes() for b in self._raw)
            out = {
                "raw_blocks": len(self._raw),
                "raw_points": raw_pts,
                "derived_blocks": len(derived),
                "derived_points": sum(b.count for b in derived),
                "head_points": len(self._head_ts) + pend_pts,
                "series": (
                    len(self._head_keys) * len(self._head_cols)
                    if self._head_ts
                    else (
                        len(self._raw[-1].keys) * len(self._raw[-1].cols)
                        if self._raw
                        else 0
                    )
                ),
                "compressed_bytes": comp_bytes,
                "rollup_blocks": {
                    _TIER_NAMES[t]: len(v) for t, v in self._rollups.items()
                },
                "sketch_blocks": {
                    _TIER_NAMES[t]: len(v) for t, v in self._sketches.items()
                },
                "sketch_bytes": sum(
                    s.nbytes() for v in self._sketches.values() for s in v
                ),
                "persisted": bool(self.path),
                "read_only": self.read_only,
                "last_disk_error": self.last_disk_error,
            }
        if self.rule_engine is not None:
            out["rules"] = self.rule_engine.stats()
        if self.snapshot_dir:
            out["snapshots"] = {
                "dir": self.snapshot_dir,
                "taken": self.snapshots_taken,
                "last": self.last_snapshot,
                "last_error": self.last_snapshot_error,
            }
        hot_lo = self._hot_earliest_ms(0)
        hot_hi = self._hot_latest_ms()
        # span_s keeps its pre-18 meaning (hot raw span) — migrations
        # and tests reason about "what survived in THIS directory"
        out["span_s"] = (
            round((hot_hi - hot_lo) / 1000.0, 1)
            if hot_lo is not None and hot_hi is not None
            else 0.0
        )
        cold = self.cold
        # the TRUE queryable horizon: hot ∪ verified cold, quarantined
        # bundles excluded (they left the catalog) — what /api/range can
        # actually answer, not what this directory happens to hold.
        # earliest_ms refreshes the cold catalog, so it runs BEFORE
        # cold.status() — one stats() doc never contradicts itself
        lo = self.earliest_ms(0)
        for t in TIERS_MS:
            tl = self.earliest_ms(t)
            if tl is not None and (lo is None or tl < lo):
                lo = tl
        hi = self.latest_ms()
        if cold is not None:
            out["cold"] = cold.status()
        out["horizon"] = {
            "earliest_ms": lo,
            "latest_ms": hi,
            "hot_earliest_ms": hot_lo,
            "cold_earliest_ms": (
                cold.status_earliest_ms() if cold is not None else None
            ),
            "queryable_span_s": (
                round((hi - lo) / 1000.0, 1)
                if lo is not None and hi is not None
                else 0.0
            ),
        }
        return out

    def cold_degrade_info(self, start_ms: int) -> "dict | None":
        """Non-None when a query window starting at ``start_ms`` may be
        missing archived history because the cold store is unreachable —
        the signal query.py turns into ``partial: true``.  Windows fully
        inside hot coverage answer completely and stay non-partial."""
        cold = self.cold
        if cold is None:
            return None
        cold.refresh()
        if not cold.unreachable:
            return None
        hot_lo = self._hot_earliest_ms(0)
        for t in TIERS_MS:
            tl = self._hot_earliest_ms(t)
            if tl is not None and (hot_lo is None or tl < hot_lo):
                hot_lo = tl
        if hot_lo is not None and start_ms >= hot_lo:
            return None
        return {
            "cold_unreachable": True,
            "hot_earliest_ms": hot_lo,
            "error": cold.last_error,
        }
