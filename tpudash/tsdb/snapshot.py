"""Online snapshots of a live TSDB: hardlinked segment sets + a
CRC-framed manifest, restorable only when provably complete.

Why hardlinks work here: segment files are append-only (store.py writes
whole CRC-framed records under the dedicated ``_io_lock`` and never
rewrites), so a hardlink shares the inode with the live file and the
byte range ``[0, size-at-snapshot)`` is immutable forever.  The manifest
records that size (captured *under* ``_io_lock``, so it always lands on
a record boundary) plus a CRC32 of exactly those bytes; restore copies
and verifies exactly that range, ignoring whatever the live store
appended after the cut.  The only ingest-visible cost of a snapshot is
the head cut (one pointer swap under the in-memory lock) — appends never
wait on the link/CRC/copy work, which the bench's ingest-stall guard
pins (``bench_snapshot``).

Torn-snapshot posture: a snapshot is assembled in a ``.snap-*.tmp``
staging directory and renamed into place only after every hardlink
landed and the manifest (written last) fsynced.  A crash — or ``kill
-9`` — at ANY point leaves either a complete, manifest-sealed snapshot
or an ignorable staging dir that GC sweeps; there is no state from
which :func:`restore_snapshot` would silently load a partial store
(the killall drill SIGKILLs a snapshotting process mid-flight and
asserts exactly this).

Restore refuses, never guesses: a manifest whose frame CRC fails, a
listed segment that is missing/short/CRC-mismatched, or a non-empty
destination all raise :class:`SnapshotError` before a single byte is
copied.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import shutil
import time
import zlib

from tpudash import wireids
from tpudash.tsdb.store import _FRAME_HDR, _MAGIC

log = logging.getLogger(__name__)

#: manifest record type inside the shared TSB1 framing (segments use
#: 1 = block, 2 = rollup)
_REC_MANIFEST = wireids.TSB1_REC_SNAPSHOT_MANIFEST
MANIFEST_NAME = "MANIFEST"
#: staging dirs older than this are dead snapshot attempts → GC fodder
_STAGING_GRACE_S = 3600.0


class SnapshotError(Exception):
    """Snapshot could not be taken, or a snapshot set failed validation
    — the message names the file and the mismatch."""


def _crc_file(path: str, nbytes: int) -> int:
    """CRC32 over exactly the first ``nbytes`` of ``path`` (the
    immutable prefix a hardlinked live segment shares with the
    snapshot)."""
    crc = 0
    remaining = nbytes
    with open(path, "rb") as f:
        while remaining > 0:
            chunk = f.read(min(1 << 20, remaining))
            if not chunk:
                raise SnapshotError(
                    f"{path}: wanted {nbytes} bytes, file ended "
                    f"{remaining} short"
                )
            crc = zlib.crc32(chunk, crc)
            remaining -= len(chunk)
    return crc


def _snapshot_name(now_ms: int) -> str:
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now_ms / 1000.0))
    return f"snap-{stamp}-{now_ms % 1000:03d}-{os.getpid()}"


def _fsync_dir(path: str) -> None:
    with contextlib.suppress(OSError):
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def write_manifest(path: str, doc: dict) -> None:
    payload = json.dumps(doc, separators=(",", ":")).encode()
    frame = _FRAME_HDR.pack(
        _MAGIC, _REC_MANIFEST, len(payload), zlib.crc32(payload)
    ) + payload
    with open(path, "wb") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())


def parse_manifest(data: bytes, label: str = "manifest") -> dict:
    """Bytes-level manifest parse + validation (the decode boundary the
    wire fuzzer drives directly); raises SnapshotError on a torn or
    corrupt image."""
    if len(data) < _FRAME_HDR.size:
        raise SnapshotError(f"{label}: manifest shorter than its frame header")
    try:
        magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(data, 0)
    except struct.error as e:  # belt-and-braces: length checked above
        raise SnapshotError(f"{label}: manifest frame unreadable: {e}") from e
    payload = data[_FRAME_HDR.size : _FRAME_HDR.size + plen]
    if (
        magic != _MAGIC
        or rec_type != _REC_MANIFEST
        or len(payload) != plen
        or zlib.crc32(payload) != crc
    ):
        raise SnapshotError(
            f"{label}: manifest frame failed magic/CRC validation "
            "(torn or corrupt — refusing the whole snapshot)"
        )
    try:
        doc = json.loads(payload)
    except ValueError as e:
        raise SnapshotError(f"{label}: manifest payload is not JSON") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("files"), list):
        raise SnapshotError(f"{label}: manifest missing its file list")
    for entry in doc["files"]:
        if not isinstance(entry, dict):
            raise SnapshotError(f"{label}: manifest file entry is not an object")
    return doc


def read_manifest(snap_dir: str) -> dict:
    """Parse + validate a snapshot's manifest; raises SnapshotError on a
    missing/torn/corrupt one (a dir without a valid manifest is not a
    snapshot, whatever else it contains)."""
    path = os.path.join(snap_dir, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise SnapshotError(f"{snap_dir}: no readable manifest ({e})") from e
    return parse_manifest(data, label=path)


def take_snapshot(store, root: str, cut_head: bool = True) -> dict:
    """One online snapshot of ``store`` into a new timestamped directory
    under ``root``.  Returns ``{dir, files, bytes, duration_ms}``.

    ``cut_head=True`` (the CLI path) seals the not-yet-full head first so
    the snapshot carries everything up to "now"; the store's automatic
    cadence passes False — it runs at the tail of a seal drain, where
    re-entering the seal gate would deadlock and the head was just cut
    anyway."""
    t0 = time.perf_counter()
    if not store.path:
        raise SnapshotError(
            "store is memory-only — snapshots need TPUDASH_TSDB_PATH"
        )
    if cut_head:
        store.flush(seal_partial=True)
    if store.last_disk_error:
        raise SnapshotError(
            f"segment writes are degraded ({store.last_disk_error}); "
            "a snapshot now would miss sealed data"
        )
    now_ms = int(time.time() * 1000)  # tpulint: allow[wall-clock] snapshot names/manifest carry epoch stamps
    name = _snapshot_name(now_ms)
    staging = os.path.join(root, f".{name}.tmp")
    entries: "list[dict]" = []
    try:
        # inside the try: an unmountable/read-only root must surface as
        # SnapshotError (the auto-snapshot path catches exactly that —
        # a bad snapshot volume must not kill the seal thread)
        os.makedirs(root, exist_ok=True)
        os.makedirs(staging)
        # sizes + links under the segment-I/O lock: writes append whole
        # CRC-framed records under this lock, so every captured size
        # lands on a record boundary (point-in-time consistency even
        # mid-seal), and reclaim cannot unlink a file out from under us
        with store._io_lock:  # tpulint: allow[blocking-under-lock] dedicated segment-I/O lock (save_history pattern): link() is a metadata op, sizes must be record-aligned
            for tier, segs in store._segs.items():
                for _seq, path, newest in segs:
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue  # never materialized (no record yet)
                    if size <= 0:
                        continue
                    base = os.path.basename(path)
                    os.link(path, os.path.join(staging, base))
                    entries.append(
                        {
                            "name": base,
                            "tier": tier,
                            "bytes": int(size),
                            "newest_ms": int(newest),
                        }
                    )
        # CRC outside every lock: the linked prefix [0, bytes) is
        # immutable, so this races nothing
        for e in entries:
            e["crc32"] = _crc_file(
                os.path.join(staging, e["name"]), e["bytes"]
            )
        write_manifest(
            os.path.join(staging, MANIFEST_NAME),
            {
                "version": 1,
                "created_ms": now_ms,
                "store_path": os.path.abspath(store.path),
                "files": entries,
            },
        )
        final = os.path.join(root, name)
        os.rename(staging, final)
        _fsync_dir(root)
    except OSError as e:
        # disk full / dead volume mid-snapshot: degrade cleanly — remove
        # the staging dir so no manifest-less husk masquerades as a
        # snapshot, and surface the error to the caller
        shutil.rmtree(staging, ignore_errors=True)
        raise SnapshotError(f"snapshot into {root} failed: {e}") from e
    gc_snapshots(
        root,
        keep=getattr(store, "snapshot_keep", 5),
        retention_s=getattr(store, "snapshot_retention_s", 0.0),
        retire_ok=cold_retire_ok(store),
    )
    out = {
        "dir": final,
        "files": len(entries),
        "bytes": sum(e["bytes"] for e in entries),
        "duration_ms": round((time.perf_counter() - t0) * 1e3, 2),
    }
    log.info(
        "tsdb snapshot %s: %d segment file(s), %d bytes in %.1f ms",
        final, out["files"], out["bytes"], out["duration_ms"],
    )
    return out


def verify_snapshot(snap_dir: str) -> dict:
    """Validate a snapshot set end to end WITHOUT copying anything:
    manifest framing, then every listed segment present with at least
    its recorded bytes and a matching CRC over exactly that prefix.
    Returns the manifest.  Raises SnapshotError naming the first
    mismatch — a torn set must be refused, never partially trusted."""
    doc = read_manifest(snap_dir)
    for e in doc["files"]:
        path = os.path.join(snap_dir, str(e["name"]))
        want = int(e["bytes"])
        try:
            size = os.path.getsize(path)
        except OSError as err:
            raise SnapshotError(
                f"{snap_dir}: manifest lists {e['name']} but it is "
                f"missing ({err})"
            ) from err
        if size < want:
            raise SnapshotError(
                f"{snap_dir}/{e['name']}: torn — {size} bytes on disk, "
                f"manifest recorded {want}"
            )
        got = _crc_file(path, want)
        if got != int(e["crc32"]):
            raise SnapshotError(
                f"{snap_dir}/{e['name']}: CRC mismatch over its "
                f"{want}-byte snapshot prefix (manifest "
                f"{e['crc32']:#010x}, file {got:#010x})"
            )
    return doc


def restore_snapshot(snap_dir: str, dest_dir: str) -> dict:
    """Restore a verified snapshot into an EMPTY directory.  All-or-
    nothing: validation runs first (see :func:`verify_snapshot`); a copy
    failure mid-restore cleans the destination back out before raising,
    so there is never a silently partial store to open."""
    doc = verify_snapshot(snap_dir)
    os.makedirs(dest_dir, exist_ok=True)
    leftover = [n for n in os.listdir(dest_dir) if not n.startswith(".")]
    if leftover:
        raise SnapshotError(
            f"restore destination {dest_dir} is not empty "
            f"(found {leftover[:3]}…); restore into a fresh directory "
            "and swap it in"
        )
    copied: "list[str]" = []
    try:
        for e in doc["files"]:
            src = os.path.join(snap_dir, str(e["name"]))
            dst = os.path.join(dest_dir, str(e["name"]))
            want = int(e["bytes"])
            with open(src, "rb") as fin, open(dst, "wb") as fout:
                remaining = want
                while remaining > 0:
                    chunk = fin.read(min(1 << 20, remaining))
                    if not chunk:
                        raise SnapshotError(
                            f"{src} shrank mid-restore"
                        )
                    fout.write(chunk)
                    remaining -= len(chunk)
                fout.flush()
                os.fsync(fout.fileno())
            copied.append(dst)
        _fsync_dir(dest_dir)
    except (OSError, SnapshotError) as e:
        for path in copied:
            with contextlib.suppress(OSError):
                os.remove(path)
        if isinstance(e, SnapshotError):
            raise
        raise SnapshotError(f"restore into {dest_dir} failed: {e}") from e
    return {
        "dir": dest_dir,
        "files": len(doc["files"]),
        "bytes": sum(int(e["bytes"]) for e in doc["files"]),
        "created_ms": doc.get("created_ms"),
    }


def list_snapshots(root: str) -> "list[str]":
    """Complete snapshot dirs under ``root``, oldest first (names embed
    their UTC timestamp, so lexical order is temporal order)."""
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    out = []
    for n in names:
        full = os.path.join(root, n)
        if n.startswith("snap-") and os.path.isdir(full) and os.path.exists(
            os.path.join(full, MANIFEST_NAME)
        ):
            out.append(full)
    return out


def cold_retire_ok(store):
    """``retire_ok`` predicate for :func:`gc_snapshots` when ``store``
    has a cold tier attached (None otherwise — pre-18 behaviour).

    A snapshot may be deleted only when each segment file it carries
    survives elsewhere: folded into a VERIFIED archive bundle, or still
    present in the live directory at ≥ the snapshotted length.  A dark
    store freezes coverage, so GC pauses; a later quarantine re-opens
    the gate and the snapshot survives as the recovery copy."""
    cold = getattr(store, "cold", None)
    if cold is None:
        return None

    def _ok(snap_dir: str) -> bool:
        try:
            files = read_manifest(snap_dir).get("files", [])
        except SnapshotError:
            return True  # corrupt manifest: worthless as a backup
        for e in files:
            name, nbytes = e.get("name", ""), int(e.get("bytes", 0))
            if cold.covers_segment(name, nbytes):
                continue
            try:
                if os.path.getsize(os.path.join(store.path, name)) >= nbytes:
                    continue
            except OSError:
                pass
            return False
        return True

    return _ok


def gc_snapshots(
    root: str, keep: int = 5, retention_s: float = 0.0,
    retire_ok=None,
) -> "list[str]":
    """Retention-aware snapshot GC: keep the newest ``keep`` complete
    snapshots, additionally dropping ones older than ``retention_s``
    (0 = no age limit) — but the newest complete snapshot ALWAYS
    survives (never delete the only backup).  Dead ``.snap-*.tmp``
    staging dirs past a grace period are swept too.  Returns what was
    removed.

    ``retire_ok`` (optional ``path -> bool``) is the cold-tier
    durability gate: a snapshot it vetoes is kept regardless of count
    or age — when archives are the only long-horizon copy, retention
    must never outrank an unverified upload (same contract as segment
    reclaim, :meth:`TSDB._reclaim_segments`)."""
    removed: "list[str]" = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return removed
    now = time.time()  # tpulint: allow[wall-clock] GC compares snapshot epoch ages
    complete = list_snapshots(root)
    victims = set(complete[: max(0, len(complete) - max(1, int(keep)))])
    if retention_s and retention_s > 0:
        cutoff_ms = (now - retention_s) * 1000.0
        for full in complete[:-1]:  # the newest always survives
            try:
                created = read_manifest(full).get("created_ms", 0)
            except SnapshotError:
                continue
            if created < cutoff_ms:
                victims.add(full)
    if retire_ok is not None:
        victims = {v for v in victims if retire_ok(v)}
    for full in sorted(victims):
        shutil.rmtree(full, ignore_errors=True)
        removed.append(full)
    for n in names:
        if not (n.startswith(".snap-") and n.endswith(".tmp")):
            continue
        full = os.path.join(root, n)
        with contextlib.suppress(OSError):
            if now - os.path.getmtime(full) > _STAGING_GRACE_S:
                shutil.rmtree(full, ignore_errors=True)
                removed.append(full)
    if removed:
        log.info("tsdb snapshot GC removed %d dir(s)", len(removed))
    return removed
