"""The cold-tier compactor: fold sealed segment files into verified
archive bundles, off the seal thread (and preferably off the leader).

Runs wherever the segment directory is readable — on the leader as a
background thread, or on a **follower** pointed at the leader's
directory (the ROADMAP posture: compaction on followers so leaders
never pay; the object store is shared, so the leader's reclaim gate
sees follower-built bundles through its own catalog refresh).

The crash-safety protocol is upload-then-verify-then-retire, with a
failure assumed at every arrow::

    pick sealed candidates ──► stage bundle locally ──► upload with
    decorrelated-backoff retry under a deadline ──► read the object
    BACK and re-verify the whole-bundle digest ──► only then does the
    bundle enter the catalog (making its source segments
    reclaim-eligible; store.py's retention pass refuses to delete
    anything the catalog does not cover)

A SIGKILL or ENOSPC at any instant therefore leaves one of exactly two
states: a complete, verified bundle (registered or re-discovered by
the next catalog refresh), or an ignorable husk (a torn staging file /
a partial object that fails its digest and is rebuilt under the same
deterministic key).  Bundle keys are derived from the source segment
set, so a crashed-and-restarted compaction run converges on the same
object instead of accumulating duplicates — the coldstorm drill
(python -m tpudash.chaos coldstorm) kill -9s this loop mid-upload,
twice, and asserts exactly that.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import random
import re
import struct
import threading
import time
import zlib

from tpudash.tsdb.cold import (
    BUNDLE_PREFIX,
    BUNDLE_SUFFIX,
    BundleError,
    build_bundle,
    parse_bundle,
)
from tpudash.tsdb.objstore import ObjectStoreError
from tpudash.tsdb.store import (
    _FRAME_HDR,
    _MAGIC,
    _REC_BLOCK,
    _REC_ROLLUP,
    _REC_SKETCH,
    _parse_block,
    _parse_rollup,
    _parse_sketch,
)

log = logging.getLogger(__name__)

_SEG_NAME = re.compile(r"^(raw|1m|10m)-(\d{6})\.seg$")
#: dead staging files older than this are crash husks → swept
_STAGE_GRACE_S = 3600.0
#: decorrelated-jitter backoff bounds for upload retries, seconds
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 5.0


class Compactor:
    """Background folding of sealed segments into archive bundles.

    ``source_dir`` is a segment directory (own, or a leader's — only
    ever read); ``cold`` is the :class:`~tpudash.tsdb.cold.ColdTier`
    sharing the target store.  ``include_tail`` additionally folds each
    tier's current append target — only safe against a CLOSED store
    (the one-shot CLI / drill path)."""

    def __init__(
        self,
        source_dir: str,
        cold,
        interval_s: float = 300.0,
        min_age_s: float = 0.0,
        max_bundle_bytes: int = 64 << 20,
        upload_deadline_s: float = 120.0,
        include_tail: bool = False,
        stage_dir: str = "",
    ) -> None:
        self.source_dir = source_dir
        self.cold = cold
        self.interval_s = max(1.0, float(interval_s))
        self.min_age_s = max(0.0, float(min_age_s))
        self.max_bundle_bytes = max(1 << 20, int(max_bundle_bytes))
        self.upload_deadline_s = max(1.0, float(upload_deadline_s))
        self.include_tail = bool(include_tail)
        self.stage_dir = stage_dir or os.path.join(cold.cache_dir, "stage")
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._rng = random.Random(os.getpid())
        self.runs = 0
        self.bundles_written = 0
        self.bytes_uploaded = 0
        self.upload_retries = 0
        self.last_error: "str | None" = None
        self.last_run_ts: "float | None" = None
        self.last_summary: "dict | None" = None
        cold.compactor = self

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tsdb-compact", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — the compaction loop must survive one bad pass  # tpulint: allow[broad-except] background cadence: one failed pass logs, the next retries
                self.last_error = str(e)
                log.warning("cold compaction pass failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        self._thread = None

    # -- one pass ------------------------------------------------------------
    def run_once(self) -> dict:
        """One compaction pass.  Returns a summary dict (also kept on
        ``last_summary`` for status())."""
        t0 = time.perf_counter()
        summary = {
            "candidates": 0,
            "bundles_written": 0,
            "sections": 0,
            "bytes_uploaded": 0,
            "upload_retries": 0,
            "gave_up": 0,
            "skipped": None,
            "duration_ms": 0.0,
        }
        self.runs += 1
        self.last_run_ts = time.time()  # tpulint: allow[wall-clock] operator-facing "last ran at" stamp
        self._sweep_stage()
        self.cold.refresh(force=True)
        if self.cold.unreachable:
            summary["skipped"] = "store unreachable"
            self.last_summary = summary
            return summary
        groups = self._candidate_groups(summary)
        for group in groups:
            if self._stop.is_set():
                break
            folded = self._fold(group)
            if folded is None:
                continue
            sections, sources, keys, cols = folded
            now_ms = int(time.time() * 1000)  # tpulint: allow[wall-clock] manifests carry epoch stamps
            data, manifest = build_bundle(
                sections, sources, now_ms, keys, cols
            )
            key = BUNDLE_PREFIX + _bundle_name(manifest, sources)
            staged = self._stage(key, data)
            ok = self._upload_verify(key, data, manifest, summary)
            if staged:
                with contextlib.suppress(OSError):
                    os.remove(staged)
            if not ok:
                summary["gave_up"] += 1
                continue
            self.cold.register(key, manifest)
            summary["bundles_written"] += 1
            summary["sections"] += len(sections)
            summary["bytes_uploaded"] += len(data)
            self.bundles_written += 1
            self.bytes_uploaded += len(data)
            log.info(
                "cold bundle %s: %d section(s), %d bytes from %d segment(s)",
                key, len(sections), len(data), len(sources),
            )
        summary["duration_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        self.last_summary = summary
        return summary

    def _candidate_groups(self, summary: dict) -> "list[list[tuple]]":
        """Sealed, aged, not-yet-covered segment files, grouped into
        bundle-sized sets.  Each tier's highest-sequence file is the
        live append target — excluded unless ``include_tail``."""
        try:
            names = sorted(os.listdir(self.source_dir))
        except OSError as e:
            summary["skipped"] = f"source dir unreadable: {e}"
            return []
        per_tier: "dict[str, list]" = {}
        for n in names:
            m = _SEG_NAME.match(n)
            if m:
                per_tier.setdefault(m.group(1), []).append(
                    (int(m.group(2)), n)
                )
        now = time.time()  # tpulint: allow[wall-clock] segment age gating compares file mtimes
        candidates: "list[tuple]" = []
        for tier, entries in per_tier.items():
            entries.sort()
            if not self.include_tail:
                entries = entries[:-1]  # the live append target
            for _seq, name in entries:
                full = os.path.join(self.source_dir, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue  # reclaimed between listdir and stat
                if st.st_size <= 0:
                    continue
                if self.min_age_s and now - st.st_mtime < self.min_age_s:
                    continue
                if self.cold.covers_segment(name, st.st_size):
                    continue
                candidates.append((tier, name, full, int(st.st_size)))
        summary["candidates"] = len(candidates)
        groups: "list[list[tuple]]" = []
        cur: "list[tuple]" = []
        cur_bytes = 0
        for item in candidates:
            if cur and cur_bytes + item[3] > self.max_bundle_bytes:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(item)
            cur_bytes += item[3]
        if cur:
            groups.append(cur)
        return groups

    def _fold(self, group: "list[tuple]"):
        """Parse every complete CRC-framed record out of the group's
        segment files into bundle sections.  A torn tail ends a file's
        content (the hot loader drops it the same way) but the file
        still counts as fully folded — unreadable garbage is not a
        reason to hold its reclaim hostage forever."""
        sections: list = []
        sources: list = []
        keys: set = set()
        cols: set = set()
        for _tier, name, full, size in group:
            try:
                with open(full, "rb") as f:
                    data = f.read()
            except OSError as e:
                log.warning("cold compaction: %s unreadable: %s", full, e)
                continue
            off = 0
            while off + _FRAME_HDR.size <= len(data):
                magic, rec_type, plen, crc = _FRAME_HDR.unpack_from(data, off)
                payload = data[off + _FRAME_HDR.size
                               : off + _FRAME_HDR.size + plen]
                if (
                    magic != _MAGIC
                    or len(payload) != plen
                    or zlib.crc32(payload) != crc
                ):
                    break  # torn tail / corruption: sealed prefix only
                try:
                    if rec_type == _REC_BLOCK:
                        b = _parse_block(payload)
                        sections.append((rec_type, 0, b.t0, b.t1, payload))
                        keys.update(b.keys)
                        cols.update(b.cols)
                    elif rec_type == _REC_ROLLUP:
                        r = _parse_rollup(payload)
                        sections.append(
                            (rec_type, r.tier_ms, r.src_t0, r.src_t1, payload)
                        )
                        keys.update(r.keys)
                        cols.update(r.cols)
                    elif rec_type == _REC_SKETCH:
                        s = _parse_sketch(payload)
                        sections.append(
                            (rec_type, s.tier_ms, s.src_t0, s.src_t1, payload)
                        )
                        cols.update(s.cols)
                        keys.update(
                            k for k in s.keys if not str(k).startswith("__")
                        )
                    # unknown record types (newer writer): skipped — the
                    # sparse index must only promise sections it can
                    # name, and the live segment set still holds them
                except (ValueError, KeyError, struct.error) as e:
                    log.warning(
                        "cold compaction: %s record @%d unparseable (%s); "
                        "stopping this file", full, off, e,
                    )
                    break
                off += _FRAME_HDR.size + plen
            sources.append({"name": name, "bytes": size})
        if not sections:
            return None
        return sections, sources, keys, cols

    # -- staging + upload ----------------------------------------------------
    def _stage(self, key: str, data: bytes) -> "str | None":
        """Bundle bytes to local disk before the upload — a crash mid-
        build can then never leave a half-written object as the only
        copy, and the husk a kill -9 leaves here is swept by age."""
        try:
            os.makedirs(self.stage_dir, exist_ok=True)
            path = os.path.join(
                self.stage_dir, os.path.basename(key) + ".staging"
            )
            with open(path, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            return path
        except OSError as e:
            # staging is belt-and-braces; ENOSPC here must not block the
            # upload (the object store is the durable copy)
            log.warning("cold staging failed (%s); uploading unstaged", e)
            return None

    def _sweep_stage(self) -> None:
        try:
            names = os.listdir(self.stage_dir)
        except OSError:
            return
        now = time.time()  # tpulint: allow[wall-clock] husk sweeping compares file mtimes
        for n in names:
            full = os.path.join(self.stage_dir, n)
            with contextlib.suppress(OSError):
                if now - os.path.getmtime(full) > _STAGE_GRACE_S:
                    os.remove(full)

    def _upload_verify(
        self, key: str, data: bytes, manifest: dict, summary: dict
    ) -> bool:
        """PUT + digest read-back under the deadline, decorrelated-
        jitter backoff between attempts.  False = gave up this pass
        (the deterministic key makes the next pass idempotent)."""
        deadline = time.monotonic() + self.upload_deadline_s
        sleep_s = _BACKOFF_BASE_S
        while True:
            try:
                self.cold.store.put(key, data)
                back = self.cold.store.get(key)
                got = parse_bundle(back, verify_digest=True)
                if len(back) != len(data) or got.get("digest") != manifest["digest"]:
                    raise BundleError("read-back returned a different bundle")
                return True
            except (ObjectStoreError, BundleError) as e:
                self.last_error = str(e)
                summary["upload_retries"] += 1
                self.upload_retries += 1
                # a torn object must not linger under the final key
                # looking complete to a lister (delete is best-effort;
                # the digest read-back is what actually protects readers)
                with contextlib.suppress(ObjectStoreError):
                    self.cold.store.delete(key)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    log.warning(
                        "cold upload of %s gave up under its deadline: %s",
                        key, e,
                    )
                    return False
                time.sleep(min(remaining, sleep_s))
                sleep_s = min(
                    _BACKOFF_CAP_S,
                    self._rng.uniform(_BACKOFF_BASE_S, sleep_s * 3),
                )

    def status(self) -> dict:
        return {
            "source": self.source_dir,
            "running": self._thread is not None and self._thread.is_alive(),
            "interval_s": self.interval_s,
            "runs": self.runs,
            "bundles_written": self.bundles_written,
            "bytes_uploaded": self.bytes_uploaded,
            "upload_retries": self.upload_retries,
            "last_run_ts": self.last_run_ts,
            "last_error": self.last_error,
            "last_summary": self.last_summary,
        }


def _bundle_name(manifest: dict, sources: "list[dict]") -> str:
    """Deterministic bundle object name from the source segment set —
    a re-run after any crash converges on the same key."""
    h = hashlib.sha256(
        "|".join(f"{s['name']}:{s['bytes']}" for s in sources).encode()
    ).hexdigest()[:12]
    return f"bundle-{manifest['t0']}-{manifest['t1']}-{h}{BUNDLE_SUFFIX}"
