"""Locally-served browser assets — the zero-egress rich-rendering path.

The reference gets offline charting for free: plotly ships as a pinned
Python dependency (reference uv.lock pins plotly 6.0.1; pyproject.toml:7-12)
and Streamlit serves every browser asset itself, so an air-gapped cluster
still renders the full interactive UI.  tpudash matches that by serving a
vendored ``plotly.min.js`` from the dashboard process when one is
available, falling back to the CDN (and then to the built-in
dependency-free renderer) only when it is not.

Resolution order for the vendored file:

1. ``TPUDASH_ASSETS_DIR`` (Config.assets_dir) — an operator-provided
   directory containing ``plotly.min.js``.
2. The packaged assets directory (``tpudash/app/assets/``) — where the
   Docker build drops the file extracted from the pinned plotly wheel
   (``deploy/fetch_plotly.py``).
3. An importable ``plotly`` Python package — its wheel carries the exact
   bundle at ``plotly/package_data/plotly.min.js`` (how the reference's
   own chart stack ships the JS).

The file is resolved once at server construction: asset presence is a
deploy-time property, and a per-request stat would put a syscall on the
index path for nothing.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

PLOTLY_ASSET_NAME = "plotly.min.js"

#: The plotly PYTHON package version whose bundled plotly.js matches the
#: page contract (html.PLOTLY_VERSION = 2.32.0): plotly.py 5.22.0 ships
#: exactly plotly.js 2.32.0.  Kept in lockstep with
#: deploy/fetch_plotly.PLOTLY_PIN (pinned equal by tests/test_assets.py).
PLOTLY_WHEEL_PIN = "5.22.0"

#: Packaged drop point for the vendored bundle (kept in-tree as a
#: directory so the wheel/package_data machinery has a stable home for it).
PACKAGED_ASSETS_DIR = os.path.join(os.path.dirname(__file__), "assets")


def find_plotly_asset(assets_dir: str = "") -> "str | None":
    """Absolute path of the vendored plotly bundle, or None.

    A configured ``assets_dir`` that exists but lacks the file is
    reported (log) rather than silently skipped — the operator pointed at
    the wrong directory and would otherwise debug a degraded page.
    """
    if assets_dir:
        path = os.path.join(assets_dir, PLOTLY_ASSET_NAME)
        if os.path.isfile(path):
            return os.path.abspath(path)
        log.warning(
            "TPUDASH_ASSETS_DIR=%s has no %s — falling back",
            assets_dir,
            PLOTLY_ASSET_NAME,
        )
    packaged = os.path.join(PACKAGED_ASSETS_DIR, PLOTLY_ASSET_NAME)
    if os.path.isfile(packaged):
        return packaged
    try:
        import plotly

        # the URL is version-stamped (html.PLOTLY_LOCAL_URL) and served
        # with a long max-age: serving whatever plotly.js an arbitrary
        # installed plotly happens to bundle would break both the page
        # contract and the cache-busting guarantee — only the pinned
        # package qualifies
        if getattr(plotly, "__version__", None) == PLOTLY_WHEEL_PIN:
            bundled = os.path.join(
                os.path.dirname(plotly.__file__),
                "package_data",
                PLOTLY_ASSET_NAME,
            )
            if os.path.isfile(bundled):
                return bundled
        else:
            # warning, not info: an air-gapped deploy relying on this
            # path degrades to the built-in renderer, and the operator
            # debugging that needs the reason at default log level
            log.warning(
                "installed plotly %s != pinned %s: not serving its bundle",
                getattr(plotly, "__version__", "?"),
                PLOTLY_WHEEL_PIN,
            )
    except ImportError:
        pass
    return None
