"""Async dashboard server.

Replaces the reference's Streamlit shell (app.py:247-489): the browser polls
``/api/frame`` every refresh interval instead of the server blocking in
``while True: time.sleep(5)`` (app.py:326, 486).  Source fetches are
blocking (requests / on-chip probes), so frames are built in a worker
executor and never stall the event loop; a frame cache ensures many browser
tabs cost one scrape per interval, not one per tab.

Routes (full reference: docs/API.md):
  GET  /                      dashboard page (issues the session cookie)
  GET  /api/frame             current frame (per-session; ETag/304, gzip)
  GET  /api/stream            SSE: full frame, then value-only deltas;
                              reconnect resumes via Last-Event-ID
  POST /api/select            {"toggle": key} | {"selected": [keys]} |
                              {"all": true} | {"none": true}  (per session)
  POST /api/style             {"use_gauge": bool}  (per session)
  GET  /api/chip?key=…        single-chip drill-down
  GET  /api/history[?chip=…]  fleet-average or per-chip raw history
  GET  /api/range             long-horizon min/max/mean series from the
                              compressed trend store (tpudash.tsdb)
  GET  /api/alerts            current alert states
  GET  /api/stragglers        fleet outliers (SPMD lockstep stragglers)
  GET  /api/alert-rules.yaml  rules as a Prometheus alerting-rule file
  GET  /api/timings           stage-timing summary (tracing, SURVEY.md §5)
  GET  /api/schema            series/panels/generations/capabilities
  POST /api/profile           cProfile N frames or a JAX device trace
  GET  /api/export.csv        current wide per-chip table as CSV
  GET  /healthz               liveness (open without auth)
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import hmac
import json
import logging
import secrets
import tempfile
import time
from collections import OrderedDict

from aiohttp import web

log = logging.getLogger(__name__)

from tpudash.analysis.asynccheck import LoopLagMonitor
from tpudash.analysis.leakcheck import process_census, warm_default_executor
from tpudash.app.assets import find_plotly_asset
from tpudash.app.html import PLOTLY_LOCAL_URL, page_html
from tpudash.app.overload import OverloadGuard, bound_stream_buffers
from tpudash.app.service import DashboardService
from tpudash.app.sessions import SessionEntry, SessionStore
from tpudash.app import wire
from tpudash.broadcast.bus import BUS_TOKEN_HEADER
from tpudash.broadcast.cohort import (
    GZIP_HEADER,
    CohortHub,
    event_buffers,
    keepalive_buffer,
    parse_event_id,
)
from tpudash.config import Config, load_config
from tpudash.sources import make_source

#: per-browser session id (the reference's st.session_state scoping,
#: app.py:252-260).  No Max-Age: it lives for the browser session, exactly
#: like a Streamlit session.
SESSION_COOKIE = "tpudash_sid"

#: "the client went away" in every spelling the asyncio/aiohttp stack
#: produces: plain socket resets, aborted/broken pipes, and (aiohttp ≥
#: 3.10) the ClientConnectionResetError StreamResponse.write raises on a
#: closing transport.  One tuple, caught in one place — a disconnecting
#: browser must terminate its SSE loop silently, never as a traceback.
_CLIENT_GONE: tuple = (
    ConnectionResetError,
    ConnectionAbortedError,
    BrokenPipeError,
)
try:
    from aiohttp import ClientConnectionResetError as _CCRE

    _CLIENT_GONE = (*_CLIENT_GONE, _CCRE)
except ImportError:  # older aiohttp raises ConnectionResetError directly
    pass

#: routes exempt from admission control: liveness must never flap under
#: load, and the static shell / vendored bundle are cheap one-time loads
#: a browser needs before it can even hold a session
_NEVER_SHED = ("/healthz",)

#: typed app-storage key for the loop-lag heartbeat task — retained here
#: (not fire-and-forget) so it can't be GC'd mid-flight and shutdown can
#: cancel it (asynccheck rule ``unretained-task``)
LOOPMON_TASK = web.AppKey("loopmon_task", asyncio.Task)


def _dumps(obj) -> str:
    """Compact JSON for everything that goes on the wire: the default
    separators' spaces cost ~8% of a 256-chip frame pre-compression, for
    zero readability benefit to a machine consumer."""
    return json.dumps(obj, separators=(",", ":"))


def _accepts_gzip(header: str) -> bool:
    """RFC 9110 Accept-Encoding check for the SSE stream.  An explicit
    ``gzip`` entry takes precedence over ``*`` (most-specific wins), so
    ``gzip;q=0, *`` is a refusal even though the wildcard would allow
    it; naive substring matching would serve gzip to a client that
    explicitly refused it with ``gzip;q=0``."""
    gzip_q = None
    star_q = None
    for item in header.split(","):
        parts = item.strip().lower().split(";")
        coding = parts[0].strip()
        if coding not in ("gzip", "*"):
            continue
        q = 1.0
        for p in parts[1:]:
            p = p.strip()
            if p.startswith("q="):
                try:
                    q = float(p[2:])
                except ValueError:
                    q = 0.0
        if coding == "gzip":
            gzip_q = q if gzip_q is None else max(gzip_q, q)
        else:
            star_q = q if star_q is None else max(star_q, q)
    if gzip_q is not None:
        return gzip_q > 0
    return star_q is not None and star_q > 0


def _json_response(data, **kw) -> web.Response:
    return web.json_response(data, dumps=_dumps, **kw)


def _build_stale_body(key: "tuple | None", frame: dict) -> tuple:
    """(key, raw, gzip) for the degraded /api/frame body — executor-side
    (the dump + compress of a ~100KB frame must not run on the loop)."""
    import gzip

    raw = _dumps(dict(frame, stale=True)).encode()
    return (key, raw, gzip.compress(raw, 6))


def _build_summary_body(service: DashboardService) -> bytes:
    """Serialized /api/summary document — executor-side (a 4096-chip
    matrix dump must not run on the loop)."""
    return _dumps(service.summary_doc()).encode()


def _build_summary_body_bin(service: DashboardService) -> tuple:
    """(encoded TDB1 summary, the doc itself) — executor-side like the
    JSON twin.  The doc (matrix still the float64 block) is retained as
    a DELTA BASE: a parent that advertises this body's ETag on its next
    poll gets a kind-7 incremental body against it."""
    doc = service.summary_doc(binary=True)
    return wire.encode_summary(doc), doc


def _key_id(key: tuple) -> str:
    """Compose-cache key as an SSE event id ("dv-sv-stall")."""
    return "-".join(str(int(p)) for p in key)


def _id_key(raw: "str | None") -> "tuple | None":
    """Parse a Last-Event-ID back into a compose-cache key (None when
    absent/garbled — the stream then starts with a full frame)."""
    if not raw:
        return None
    parts = raw.strip().split("-")
    if len(parts) != 3:
        return None
    try:
        return (int(parts[0]), int(parts[1]), bool(int(parts[2])))
    except ValueError:
        return None


class DashboardServer:
    def __init__(self, service: DashboardService):
        self.service = service
        self._lock = asyncio.Lock()
        self.sessions = SessionStore(
            service.state,
            limit=service.cfg.session_limit,
            ttl=service.cfg.session_ttl,
        )
        # per-browser sessions ride the TPUDASH_STATE_PATH checkpoint: a
        # dashboard restart must not log every viewer out of their
        # selection (the reference's refresh-resets-state flaw, SURVEY §5)
        service.sessions_snapshot = self.sessions.to_dicts
        if service.cfg.state_path:
            restored = self.sessions.restore(service.restored_sessions)
            if restored:
                log.info("restored %d browser sessions", restored)
        #: bumped after every refresh_data(); pairs with each session's
        #: state_version to key the per-session compose caches
        self._data_version = 0
        self._data_at: float = 0.0
        #: (data_version, {(chip_key, use_gauge): detail}) — drill-down
        #: responses cached for the life of one data refresh
        self._chip_cache: tuple = (-1, {})
        #: a refresh that outlived the watchdog (or its awaiting handler),
        #: parked for later harvest, plus when it started
        self._refresh_task = None
        self._refresh_started: float = 0.0
        self._device_trace_active = False  # jax profiler is a singleton
        #: admission control / load shedding (tpudash.app.overload); the
        #: service's alert synthesis reads the guard through the provider
        self.overload = OverloadGuard(service.cfg)
        service.overload_provider = self.overload.snapshot
        #: most recent frame composed for ANY session — what a shed
        #: GET /api/frame degrades to (marked ``stale: true``) instead
        #: of erroring.  A plain reference swap: never mutated in place.
        self._last_frame: "dict | None" = None
        self._last_frame_key: "tuple | None" = None
        #: (key, raw body, gzip body) for the degraded response — built
        #: at most once per published frame, however many sheds serve it
        self._stale_body: "tuple | None" = None
        #: single-flight gate for that build: a shed swarm arriving on a
        #: fresh frame dispatches ONE executor build, not one per request
        self._stale_build_lock = asyncio.Lock()
        #: runtime event-loop lag sanitizer (asynccheck): callback timing
        #: with stack attribution + heartbeat lag percentiles, surfaced
        #: as ``loop_lag_ms`` on /api/timings and /healthz.  Installed by
        #: build_app's on_startup hook; budget 0 disables it.
        self.loop_monitor = LoopLagMonitor(
            budget_ms=service.cfg.loop_lag_budget
        )
        #: cohort broadcast hub (tpudash.broadcast): sessions sharing a
        #: (selection, style) state compose/delta/gzip ONCE per tick into
        #: immutable sealed buffers; the per-client SSE loop below is a
        #: pure buffer write.  In TPUDASH_WORKERS mode the supervisor
        #: publishes these same seals onto the frame bus.
        self.hub = CohortHub(
            service.compose_frame,
            _dumps,
            window=service.cfg.broadcast_window,
            max_cohorts=service.cfg.broadcast_max_cohorts,
            on_evict=self._on_cohort_evict,
            binary=service.cfg.wire_format != "json",
        )
        #: worker-tier stats provider (set by the broadcast supervisor);
        #: None → single-process mode, /api/workers reports just this one
        self.workers_provider = None
        #: frame-bus publisher (tpudash.broadcast.bus.BusPublisher, set by
        #: the supervisor in TPUDASH_WORKERS mode); None → single-process.
        #: Newly-created seals and session→cohort bindings are pushed to
        #: it so worker mirrors stay current.
        self.bus_publisher = None
        #: True when the bus publisher listens on a NETWORK address
        #: (edge tier fronting this compose): /internal/ routes are then
        #: reachable from off-host and must present the bus bearer token
        #: (``X-TPUDash-Bus-Token``) instead of being waved through on
        #: unix-transport trust
        self.bus_public = False
        self.bus_token = ""
        #: (cid → seq) of the newest seal already handed to the bus — a
        #: tick that served a cached seal must not re-publish it
        self._published_seqs: dict = {}
        #: (key, raw body) of the /api/summary document — built at most
        #: once per (data_version, hub epoch, stalled) however many
        #: federation parents poll, behind a single-flight gate; the
        #: ETag derives from the key so steady-state polls answer 304
        #: with no body and no executor work
        self._summary_cache: "tuple[tuple | None, bytes | None]" = (None, None)
        self._summary_cache_bin: "tuple[tuple | None, bytes | None]" = (
            None,
            None,
        )
        self._summary_build_lock = asyncio.Lock()
        #: recent binary summary docs keyed by their ETag — the DELTA
        #: BASES (TPUDASH_FEDERATE_SUMMARY_DELTA): a parent advertising
        #: one of these gets a kind-7 incremental body; anything older
        #: has aged out and falls back to the full doc unconditionally
        self._summary_hist: "OrderedDict[str, dict]" = OrderedDict()
        #: (base_etag, cur_etag) → body, LRU-bounded like the hist: one
        #: delta built per TRANSITION however many parents poll it — and
        #: parents at DIFFERENT bases (diamond topologies) each keep
        #: their own entry instead of thrashing one slot per poll
        self._summary_delta_cache: "OrderedDict[tuple, bytes]" = (
            OrderedDict()
        )
        #: bounded LRU of finalized ``/api/range`` response bodies keyed
        #: by canonical query params: serves the ETag/304 revalidation
        #: path AND the OverloadGuard's stale-degrade contract (a shed
        #: range poll answers slightly-old data + a stale marker instead
        #: of 503, like /api/frame).  Entries: key → (etag|None, bytes)
        self._range_cache: "OrderedDict[str, tuple]" = OrderedDict()
        #: lazy HTTP session for the federation child drill-down proxy
        #: (/api/child/...); None until the first proxied request, closed
        #: on cleanup
        self._child_session = None
        #: vendored plotly bundle (deploy-time property, resolved once);
        #: None → the page uses the CDN tag and /static 404s
        self._plotly_asset = find_plotly_asset(service.cfg.assets_dir)
        if self._plotly_asset:
            log.info("serving vendored plotly from %s", self._plotly_asset)
        #: rendered once — asset presence is fixed for the process life
        self._page = page_html(
            local_plotly=self._plotly_asset is not None,
            wire_format=service.cfg.wire_format,
        )

    async def _save_state(self) -> None:
        """Persist the composite checkpoint OFF the event loop — the
        write is blocking disk I/O and _mutate holds the frame lock.
        The session snapshot is taken HERE, on the loop: request
        handlers mutate the SessionStore from the loop, so the executor
        thread must never iterate it."""
        snapshot = self.sessions.to_dicts()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.service.save_state, snapshot)

    def _entry(self, request: web.Request) -> SessionEntry:
        return self.sessions.entry(request.cookies.get(SESSION_COOKIE))

    # -- frame caching -------------------------------------------------------
    async def _refresh_locked(
        self, force: bool, deadline: "float | None" = None
    ) -> None:
        """Refresh the shared scrape data when stale.  Caller holds _lock.

        Watchdog (Config.refresh_watchdog): a wedged source — a hung
        accelerator runtime blocks inside native code without raising, so
        no exception path fires — must not freeze every route behind this
        lock.  Past the deadline the in-flight fetch is parked, routes
        keep serving the last data with a "stalled" warning, and a later
        tick harvests the fetch when (if) it completes.  At most ONE
        fetch is ever in flight, so a wedge cannot exhaust the executor.

        ``deadline`` is the REQUEST's budget (monotonic stamp from the
        admission middleware): a request whose budget runs out stops
        waiting and serves what's cached — WITHOUT declaring a source
        stall (the source may be fine; this request just ran out of
        road).  The fetch itself keeps running for the next caller."""
        watchdog = self.service.cfg.refresh_watchdog
        stall_msg = (
            f"metrics source stalled (no response in {watchdog:g}s); "
            "serving the last good data"
        )

        def _budget() -> "float | None":
            return None if deadline is None else deadline - time.monotonic()

        if self._refresh_task is not None:
            if not self._refresh_task.done():
                # A fetch parked by the watchdog — or orphaned by a client
                # disconnect mid-wait — is still running.  Re-attach for
                # whatever watchdog budget remains (a disconnect at t=1s
                # of a healthy 3s fetch must not degrade every other
                # client to stale-instantly); only past the deadline do
                # we declare the stall and serve stale.
                elapsed = time.monotonic() - self._refresh_started
                waits = []
                if watchdog and watchdog > 0:
                    waits.append(watchdog - elapsed)
                budget = _budget()
                if budget is not None:
                    waits.append(budget)
                if not waits:
                    await asyncio.shield(self._refresh_task)
                elif min(waits) > 0:
                    try:
                        await asyncio.wait_for(
                            asyncio.shield(self._refresh_task), min(waits)
                        )
                    except asyncio.TimeoutError:
                        pass
                if not self._refresh_task.done():
                    # only a WATCHDOG expiry is a stall; a request-budget
                    # expiry serves stale silently and leaves the verdict
                    # to callers with time left
                    if (
                        watchdog
                        and watchdog > 0
                        and time.monotonic() - self._refresh_started
                        >= watchdog
                        and self.service.refresh_stalled is None
                    ):
                        self.service.refresh_stalled = stall_msg
                    return  # serve what we have
            task, self._refresh_task = self._refresh_task, None
            exc = task.exception() if not task.cancelled() else None
            if exc is not None:
                # an unexpected failure outside refresh_data's own guards:
                # log it and fall through — the staleness check below
                # starts a fresh fetch instead of stamping bad state good
                log.warning("parked refresh raised: %s", exc)
                self.service.refresh_stalled = None
            else:
                self._data_version += 1
                self.service.refresh_stalled = None
            # deliberately NOT updating _data_at: the harvested data is as
            # old as the stall — fall through so a genuinely fresh fetch
            # starts on this same tick instead of an interval later
        age = time.monotonic() - self._data_at
        if (
            force
            or self._data_version == 0
            or age >= self.service.cfg.refresh_interval
        ):
            loop = asyncio.get_running_loop()
            # parked BEFORE the await: every exit path (timeout, client
            # disconnect cancelling this handler) leaves the task tracked,
            # so at most one fetch is ever in flight no matter how many
            # impatient clients come and go
            task = loop.run_in_executor(None, self.service.refresh_data)
            self._refresh_task = task
            self._refresh_started = time.monotonic()
            waits = []
            if watchdog and watchdog > 0:
                waits.append(watchdog)
            budget = _budget()
            if budget is not None:
                waits.append(max(0.0, budget))
            try:
                if waits:
                    await asyncio.wait_for(asyncio.shield(task), min(waits))
                else:
                    await task
            except asyncio.TimeoutError:
                # watchdog expiry → stall; request-budget expiry → the
                # fetch stays parked for the next caller to harvest, and
                # THIS request serves whatever is cached
                if (
                    watchdog
                    and watchdog > 0
                    and time.monotonic() - self._refresh_started >= watchdog
                ):
                    self.service.refresh_stalled = stall_msg
                return
            self._refresh_task = None
            self._data_version += 1
            self._data_at = time.monotonic()
            self.service.refresh_stalled = None

    async def _compose_locked(
        self,
        entry: SessionEntry,
        deadline: "float | None" = None,
    ) -> "tuple[dict, tuple]":
        """Per-session compose with its (data_version, state_version) cache
        key.  Caller holds _lock and has already run _refresh_locked — the
        polling transport's cache-keying protocol (the SSE transport now
        rides the cohort hub instead, see :meth:`_stream_admitted`).

        A request whose budget (``deadline``) has already expired — it
        queued behind the lock longer than its client will wait — serves
        its cached frame instead of burning executor time on a compose
        nobody may read; with nothing cached it composes anyway (serving
        NOTHING helps no one)."""
        key = (
            self._data_version,
            entry.state_version,
            # stall transitions must invalidate cached frames — the
            # warning has to appear (and clear) without a data refresh
            bool(self.service.refresh_stalled),
        )
        if entry.frame is not None and entry.frame_key == key:
            return entry.frame, key
        if (
            deadline is not None
            and entry.frame is not None
            and time.monotonic() >= deadline
        ):
            return entry.frame, entry.frame_key
        loop = asyncio.get_running_loop()
        frame = await loop.run_in_executor(
            None, self.service.compose_frame, entry.state
        )
        entry.frame = frame
        entry.frame_key = key
        self._last_frame = frame
        self._last_frame_key = key
        return frame, key

    async def _get_frame(
        self,
        force: bool = False,
        entry: SessionEntry | None = None,
        deadline: "float | None" = None,
    ) -> dict:
        """Frame for one viewer session.  The scrape/normalize half runs at
        most once per refresh interval across ALL sessions; the per-session
        compose is cached against (data_version, state_version), so many
        tabs of one browser cost one render and a selection change on one
        session never re-scrapes or re-renders the others.  ``deadline``
        is the request budget (see _refresh_locked/_compose_locked)."""
        entry = entry if entry is not None else self.sessions.entry(None)
        async with self._lock:
            await self._refresh_locked(force, deadline=deadline)
            frame, _ = await self._compose_locked(entry, deadline=deadline)
            return frame

    def _tick_key(self) -> tuple:
        """What one broadcast tick composes from: the shared data version,
        the hub's global-invalidation epoch (silences), and whether the
        source is currently stalled (the warning must appear — and clear —
        without a data refresh)."""
        return (
            self._data_version,
            self.hub.epoch,
            bool(self.service.refresh_stalled),
        )

    async def _cohort_tick(
        self, entry: SessionEntry, ack: "tuple[int, int] | None"
    ) -> "tuple[list, tuple[int, int]]":
        """One stream tick through the cohort hub: refresh the shared data
        when stale, resolve the session's cohort, seal it (compose + delta
        + serialize + gzip ONCE for every subscriber of the cohort, cached
        across callers racing on the same tick), and pick the seals this
        subscriber still needs.  Returns ``(seals, new_ack)`` where
        ``seals`` is the delta chain to send, ``[latest]`` full-frame
        fallback, or ``[]`` keepalive — encoded as (seal, use_delta)
        pairs so the writer stays trivial."""
        async with self._lock:
            await self._refresh_locked(False)
            cohort = self.hub.resolve(entry.state)
            seal = await self.hub.seal_cohort(cohort, self._tick_key())
            self._publish_seal(seal)
            chain, ack_seq = self.hub.payloads_for(cohort, ack)
        if chain is None:
            return [(seal, False)], (cohort.cid, ack_seq)
        return [(s, True) for s in chain], (cohort.cid, ack_seq)

    async def _mutate(self, entry: SessionEntry, fn):
        """Run a state mutation under the frame lock: service renders on
        the worker thread only while the lock is held, so mutations are
        serialized against frame builds (no torn selection lists).  Bumps
        the session's state version (cache invalidation) and persists the
        checkpoint — per-browser sessions ride it too, so a restart keeps
        every viewer's selection (the reference resets on refresh,
        SURVEY §5)."""
        async with self._lock:
            result = fn()
            entry.state_version += 1
            await self._save_state()
            return result

    # -- handlers ------------------------------------------------------------
    async def index(self, request: web.Request) -> web.Response:
        resp = web.Response(text=self._page, content_type="text/html")
        if not request.cookies.get(SESSION_COOKIE):
            # first visit: issue the per-browser session id the reference
            # gets for free from Streamlit (app.py:252-260)
            resp.set_cookie(
                SESSION_COOKIE,
                secrets.token_urlsafe(16),
                httponly=True,
                samesite="Lax",
            )
        return resp

    async def plotly_asset(self, request: web.Request) -> web.StreamResponse:
        """The vendored plotly bundle (zero-egress rich rendering).  404
        when no bundle was resolved at startup — the page then carries
        the CDN tag instead, so nothing ever requests this in vain.
        Long-lived caching is safe: PLOTLY_LOCAL_URL carries the plotly
        version, so a deploy that bumps it changes the URL, and
        FileResponse still serves Last-Modified for revalidation."""
        if self._plotly_asset is None:
            raise web.HTTPNotFound(text="no vendored plotly bundle")
        return web.FileResponse(
            self._plotly_asset,
            headers={
                "Content-Type": "application/javascript",
                "Cache-Control": "public, max-age=86400",
            },
        )

    async def frame(self, request: web.Request) -> web.Response:
        """Current frame, with ETag revalidation: the polling fallback
        re-fetches every interval, and between data refreshes the frame
        is byte-identical — a conditional GET costs 304 + no body instead
        of the full ~100KB figure JSON.  Browsers do this automatically
        for fetch() under Cache-Control: no-cache."""
        entry = self._entry(request)
        frame = await self._get_frame(
            entry=entry, deadline=request.get("tpudash_deadline")
        )
        # binary negotiation (Accept: application/x-tpudash-bin): the
        # TDB1 full-frame container — columnar chip table + quantized z
        # grids — behind the very same ETag/304 revalidation.  JSON
        # stays the default for every client that doesn't ask, and the
        # knob (TPUDASH_WIRE_FORMAT=json) turns negotiation off.
        binary = (
            wire.CONTENT_TYPE in request.headers.get("Accept", "")
            and self.hub.binary
        )
        etag = (
            f'"{_key_id(entry.frame_key)}{"-b" if binary else ""}"'
            if entry.frame_key is not None
            else None
        )
        headers = {"Cache-Control": "no-cache"}
        if etag is not None:
            headers["ETag"] = etag
            if request.headers.get("If-None-Match") == etag:
                return web.Response(status=304, headers=headers)
        if binary:
            loop = asyncio.get_running_loop()
            try:
                body = await loop.run_in_executor(
                    None, wire.encode_frame, frame
                )
            except wire.WireError:
                # not template-encodable (error frame): serve JSON, and
                # without the binary validator — the representations
                # must never share an ETag
                headers.pop("ETag", None)
                return _json_response(frame, headers=headers)
            return web.Response(
                body=body, content_type=wire.CONTENT_TYPE, headers=headers
            )
        return _json_response(frame, headers=headers)

    def _summary_key(self) -> tuple:
        """What one summary body is composed from — data version, the
        hub's global-invalidation epoch (silences re-annotate the alert
        digest), and the stall flag."""
        return (
            self._data_version,
            self.hub.epoch,
            bool(self.service.refresh_stalled),
        )

    async def summary(self, request: web.Request) -> web.Response:
        """``GET /api/summary`` — the compact fleet-rollup document a
        federation parent polls (tpudash.federation): per-chip latest
        numeric columns, fleet averages, alert digest, source health.

        Steady state is near-free: the ETag derives from (data_version,
        hub epoch, stalled), so a parent whose ``If-None-Match`` still
        matches gets ``304`` with no body, no executor hop, and no
        serialization.  The body itself is built at most once per key
        behind a single-flight gate, however many parents federate this
        child.  Refreshes the shared scrape data like ``/api/frame``
        does — a child serving ONLY federation traffic must still scrape
        on its own cadence."""
        async with self._lock:
            await self._refresh_locked(
                False, deadline=request.get("tpudash_deadline")
            )
        # binary negotiation behind the SAME ETag/304 machinery: the
        # TDB1 summary ships the float64 matrix raw (the parent decodes
        # with one frombuffer instead of a JSON cell parse) — the
        # worst-case 16-child fan-in cost is summary decode × N
        binary = (
            wire.CONTENT_TYPE in request.headers.get("Accept", "")
            and self.hub.binary
        )
        key = self._summary_key()
        etag = f'"s-{_key_id(key)}{"-b" if binary else ""}"'
        headers = {
            "Cache-Control": "no-cache",
            "ETag": etag,
            # the body depends on BOTH negotiation inputs: a shared
            # cache between a child and several parents must never hand
            # one parent's kind-7 delta (anchored on ITS base) to a
            # parent holding a different one, nor a binary doc to a
            # JSON consumer
            "Vary": "Accept, X-Tpudash-Summary-Base",
        }
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304, headers=headers)
        cache_slot = "_summary_cache_bin" if binary else "_summary_cache"
        cached_key, raw = getattr(self, cache_slot)
        if cached_key != key:
            async with self._summary_build_lock:
                cached_key, raw = getattr(self, cache_slot)
                if cached_key != key:
                    loop = asyncio.get_running_loop()
                    if binary:
                        raw, doc = await loop.run_in_executor(
                            None, _build_summary_body_bin, self.service
                        )
                        self._summary_hist[f'"s-{_key_id(key)}-b"'] = doc
                        while len(self._summary_hist) > 4:
                            self._summary_hist.popitem(last=False)
                    else:
                        raw = await loop.run_in_executor(
                            None, _build_summary_body, self.service
                        )
                    setattr(self, cache_slot, (key, raw))
                    cached_key = key
        # serve the ETag of the body actually cached (the data may have
        # advanced while this request queued behind the build gate)
        etag_cur = f'"s-{_key_id(cached_key)}{"-b" if binary else ""}"'
        headers["ETag"] = etag_cur
        body = raw
        if binary:
            body = await self._summary_delta_body(request, etag_cur, raw)
        return web.Response(
            body=body,
            content_type=wire.CONTENT_TYPE if binary else "application/json",
            headers=headers,
        )

    async def _summary_delta_body(
        self, request: web.Request, etag_cur: str, raw: bytes
    ) -> bytes:
        """The incremental-summary negotiation (PR 15): a parent that
        advertised a base ETag this child still holds gets a kind-7
        delta body — changed-cell bitmap + qv cells, steady-state fan-in
        bytes ≥3× smaller; ANY mismatch (unknown base, identity change,
        knob off) serves the full doc ``raw`` unconditionally.  The
        delta is built once per (base, current) transition however many
        parents share the base."""
        if not self.service.cfg.federate_summary_delta:
            return raw
        from tpudash.federation.client import SUMMARY_BASE_HEADER

        base_etag = request.headers.get(SUMMARY_BASE_HEADER)
        if not base_etag or base_etag == etag_cur:
            return raw
        base = self._summary_hist.get(base_etag)
        cur = self._summary_hist.get(etag_cur)
        if base is None or cur is None:
            return raw
        dk = (base_etag, etag_cur)
        body = self._summary_delta_cache.get(dk)
        if body is not None:
            return body
        async with self._summary_build_lock:
            body = self._summary_delta_cache.get(dk)
            if body is not None:
                return body
            loop = asyncio.get_running_loop()
            try:
                body = await loop.run_in_executor(
                    None, wire.encode_summary_delta, cur, base, base_etag
                )
            except wire.WireError:
                # identity/shape changed across the transition — the
                # unconditional full-doc fallback
                return raw
            self._summary_delta_cache[dk] = body
            while len(self._summary_delta_cache) > 4:
                self._summary_delta_cache.popitem(last=False)
        return body

    def _child_http(self):
        """Lazy client session for the child drill-down proxy.
        ``auto_decompress=False``: child bodies pass through verbatim
        against the Accept-Encoding this hop actually forwarded."""
        if self._child_session is None:
            from aiohttp import ClientSession, ClientTimeout

            self._child_session = ClientSession(
                timeout=ClientTimeout(
                    total=max(self.service.cfg.http_timeout, 1.0)
                ),
                auto_decompress=False,
            )
        return self._child_session

    async def child_proxy(self, request: web.Request) -> web.Response:
        """``GET /api/child/{child}/{tail}`` — drill INTO a federated
        child through the fleet parent: the fleet pane's chip drill-down
        (``/api/chip``, ``/api/history``, ``/api/range``, topology…)
        answers from the child that owns the chip, with the same
        hop-header hygiene as the worker→compose proxy.  Multi-level
        fleets COMPOSE: ``/api/child/{a}/{b}/api/chip`` hops to ``a``,
        which resolves ``b`` one level down (each level re-validates
        path hygiene and re-authenticates with its own fleet token), so
        a root drill-down reaches any grandchild without the root
        knowing the grandchild's address.  An unreachable child maps to
        **502** (the child is the broken upstream — 503 would blame this
        parent, and the parent is fine); an unknown child or a non-API
        tail is 404 here; a hop chain deeper than the depth cap is 508
        (a proxy loop must burn hops, never sockets)."""
        urls_fn = getattr(self.service.source, "child_urls", None)
        if not callable(urls_fn):
            raise web.HTTPNotFound(
                text="not a federation parent (TPUDASH_FEDERATE unset)"
            )
        child = request.match_info["child"]
        url = urls_fn().get(child)
        if url is None:
            raise web.HTTPNotFound(text=f"unknown federated child {child!r}")
        tail = request.match_info["tail"]
        # dot segments would let "api/../internal/cohort" pass the
        # prefix check and NORMALIZE to a non-API child route inside the
        # client URL — reject them (aiohttp has already percent-decoded
        # the match, so encoded spellings land here too).  The hygiene
        # runs at EVERY level of a composed drill-down.
        segments = tail.split("/")
        if ".." in segments or "." in segments or "" in segments:
            raise web.HTTPNotFound(
                text="only /api/* and /healthz proxy to children"
            )
        if not (tail.startswith("api/") or tail == "healthz"):
            # multi-level drill-down: the leading segment(s) name
            # children of `child` — recompose the hop as the child's
            # own /api/child/... route.  Only when an API tail actually
            # follows; bare garbage 404s here, not one hop down.
            if "/api/" not in f"/{tail}" and not tail.endswith("/healthz"):
                raise web.HTTPNotFound(
                    text="only /api/* and /healthz proxy to children"
                )
            tail = f"api/child/{tail}"
        hops = 0
        raw_hops = request.headers.get("X-Tpudash-Proxy-Hops")
        if raw_hops:
            try:
                hops = int(raw_hops)
            except ValueError:
                hops = 0
        # refuse only when the chain would EXCEED the depth cap: a
        # max_depth chain needs exactly max_depth forwards, and the
        # data plane admits topologies that deep — the proxy must reach
        # every level the fan-in aggregates (hops is how many forwards
        # already happened; this one makes hops + 1)
        if hops >= max(1, self.service.cfg.federate_max_depth):
            # 508 Loop Detected (aiohttp has no named class for it)
            return web.Response(
                status=508,
                text=(
                    f"drill-down exceeded {hops} hops "
                    "(TPUDASH_FEDERATE_MAX_DEPTH) — a federation cycle "
                    "would otherwise proxy forever"
                ),
            )
        from aiohttp import ClientError

        from tpudash.federation.proxy import forward_headers

        # the parent's own bearer gate already admitted this request;
        # toward the child the PARENT authenticates (one fleet, one
        # token) — the client's header must not leak through as-is
        headers = forward_headers(request.headers, drop={"authorization"})
        headers["X-Tpudash-Proxy-Hops"] = str(hops + 1)
        if self.service.cfg.auth_token:
            headers["Authorization"] = (
                f"Bearer {self.service.cfg.auth_token}"
            )
        if not any(k.lower() == "accept-encoding" for k in headers):
            # same trap as the worker proxy: aiohttp's client would
            # inject "gzip, deflate" and hand an encoded body to a
            # client that never offered an encoding
            headers["Accept-Encoding"] = "identity"
        target = f"{url}/{tail}"
        if request.query_string:
            target = f"{target}?{request.query_string}"
        try:
            async with self._child_http().get(
                target, headers=headers
            ) as r:
                payload = await r.read()
                out = forward_headers(r.headers, drop={"content-length"})
                return web.Response(
                    status=r.status, body=payload, headers=out
                )
        except (OSError, asyncio.TimeoutError, ClientError) as e:
            raise web.HTTPBadGateway(
                text=f"federated child {child!r} unreachable: {e}"
            ) from e

    async def federation_register(self, request: web.Request) -> web.Response:
        """``POST /api/federation/register`` — the child-discovery
        handshake (TPUDASH_FEDERATE_DISCOVERY=register).  Body:
        ``{"name": ..., "url": ..., "leave": bool?}``.  Rides the
        ordinary bearer gate (one fleet, one token); a registered child
        re-POSTs within the returned ``ttl`` or fades live → stale →
        dark.  ``leave: true`` deregisters (the same fade — an explicit
        goodbye is never an instant vanish)."""
        src = self.service.source
        reg = getattr(src, "register_child", None)
        if not callable(reg):
            raise web.HTTPNotFound(
                text="not a federation parent (TPUDASH_FEDERATE / "
                "TPUDASH_FEDERATE_DISCOVERY unset)"
            )
        try:
            body = await request.json()
        except ValueError as e:
            raise web.HTTPBadRequest(
                text="register body must be a JSON object"
            ) from e
        if not isinstance(body, dict):
            raise web.HTTPBadRequest(
                text="register body must be a JSON object"
            )
        name = str(body.get("name") or "").strip()
        loop = asyncio.get_running_loop()
        if body.get("leave"):
            try:
                # roster persistence is file I/O — executor, never the loop
                removed = await loop.run_in_executor(
                    None, src.deregister_child, name
                )
            except PermissionError as e:
                raise web.HTTPForbidden(text=str(e)) from e
            return _json_response({"ok": True, "removed": bool(removed)})
        url = str(body.get("url") or "").strip()
        if not name or not url:
            raise web.HTTPBadRequest(
                text="register body needs non-empty name and url"
            )
        try:
            ttl = await loop.run_in_executor(None, reg, name, url)
        except PermissionError as e:
            raise web.HTTPForbidden(text=str(e)) from e
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from e
        return _json_response(
            {
                "ok": True,
                "ttl": ttl,
                # the heartbeat cadence the child should adopt
                "interval": round(max(1.0, ttl / 3.0), 3),
                "parent": getattr(src, "node_id", None),
            }
        )

    async def stream(self, request: web.Request) -> web.StreamResponse:
        """Server-sent events: push a frame every refresh interval.  All
        subscribers share the scrape; subscribers of one session share its
        serialized payload, so N open tabs still cost one scrape per
        interval and one compose per session.

        Bounded fan-out: at Config.max_streams concurrent subscribers new
        streams are shed (503 + Retry-After), and a consumer that blocks
        one event write past Config.sse_write_deadline is evicted — a
        stalled ``resp.write`` must not pin a compressor and a session
        entry forever.  Both ends of that contract are cheap for the
        client: EventSource auto-reconnects with Last-Event-ID, so an
        evicted consumer that recovers resumes on its delta path."""
        if not self.overload.acquire_stream():
            raise web.HTTPServiceUnavailable(
                text="stream capacity reached; retry shortly",
                headers={"Retry-After": self.overload.retry_after_header()},
            )
        try:
            return await self._stream_admitted(request)
        finally:
            self.overload.release_stream()

    async def _stream_admitted(
        self, request: web.Request
    ) -> web.StreamResponse:
        """The per-client SSE loop — a pure pre-encoded buffer write.

        All composing, delta-diffing, serializing, and compressing
        happens ONCE per cohort per tick in the hub (tpudash.broadcast):
        every subscriber of a cohort writes the exact same immutable
        seal buffers, so per-client marginal cost is socket I/O, not
        CPU.  Gzip subscribers get ``GZIP_HEADER`` once, then the
        cohort's shared full-flushed deflate segments — any sequence of
        such segments concatenates into one valid gzip stream, which is
        what makes per-cohort (instead of per-client) compression
        possible.

        Event ids are ``<cohort>-<seq>``; EventSource echoes the last id
        on reconnect and the cohort's retained seal window resumes the
        exact delta chain the client missed — from this process or any
        bus-mirroring worker (TPUDASH_WORKERS mode serves this same loop
        from worker processes; see tpudash.broadcast.worker)."""
        sid = request.cookies.get(SESSION_COOKIE)
        # binary negotiation: ?format=bin switches the stream to TDB1
        # event framing (full frames stay JSON inside type-1 events; the
        # steady-state deltas are the compact binary encoding).  When
        # the binary tier is disabled the request is refused up front —
        # the page's glue then falls back to the JSON EventSource path.
        binary = request.query.get("format") == "bin"
        if binary and not self.hub.binary:
            raise web.HTTPNotAcceptable(
                text="binary wire format disabled (TPUDASH_WIRE_FORMAT=json)"
            )
        headers = {
            "Content-Type": (
                wire.STREAM_CONTENT_TYPE if binary else "text/event-stream"
            ),
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        }
        accepts_gzip = _accepts_gzip(request.headers.get("Accept-Encoding", ""))
        if accepts_gzip:
            headers["Content-Encoding"] = "gzip"
        resp = web.StreamResponse(headers=headers)
        try:
            await resp.prepare(request)
        except _CLIENT_GONE:
            # client vanished between connect and headers — a premature
            # disconnect (constant under connect storms), never an error
            return resp
        bound_stream_buffers(request, self.service.cfg.sse_sndbuf)

        # Per-event drain: aiohttp's StreamWriter awaits a real transport
        # drain only every 64KB of cumulative writes, so a stalled
        # consumer would silently absorb several events of buffering
        # before the write deadline could ever engage.  Draining at event
        # boundaries makes backpressure — and therefore the slow-consumer
        # deadline — event-granular.  (No public API: _payload_writer is
        # the writer prepare() installed; drain() is its contract.)
        payload_writer = getattr(resp, "_payload_writer", None)

        async def write_buf(data: bytes) -> None:
            await resp.write(data)
            if payload_writer is not None:
                await payload_writer.drain()

        # binary clients use fetch-streaming (no EventSource), so the
        # resume ack can also arrive as a query parameter
        ack = parse_event_id(
            request.headers.get("Last-Event-ID")
            or request.query.get("last_id")
        )
        # the figure template the client CLAIMS to hold (?tpl= on
        # reconnect).  The claim is only ever compared against the
        # seal's current template id: a stale claim — reconnect across
        # a cohort epoch (compose restart, LRU evict/recreate) — simply
        # fails the comparison and the fresh template is sent BEFORE
        # any numeric section; a matching claim skips the bytes.
        tid_held = request.query.get("tpl") if binary else None
        write_deadline = self.overload.write_deadline
        try:
            if accepts_gzip:
                await write_buf(GZIP_HEADER)
            while True:
                # re-resolve every tick: touches last_seen so an actively
                # streamed session is never TTL-evicted, picks up the
                # replacement entry if it somehow was, and follows the
                # session into a NEW cohort after a selection change
                entry = self.sessions.entry(sid)
                seals, ack = await self._cohort_tick(entry, ack)
                if not seals:
                    payloads = [keepalive_buffer(accepts_gzip, binary)]
                else:
                    payloads, tid_held = event_buffers(
                        seals, accepts_gzip, binary, tid_held
                    )
                    if any(p is None for p in payloads):
                        break  # seal lacks the negotiated encoding
                evicted = False
                for payload in payloads:
                    if write_deadline and write_deadline > 0:
                        try:
                            await asyncio.wait_for(
                                write_buf(payload), write_deadline
                            )
                        except asyncio.TimeoutError:
                            # Slow-consumer eviction: the peer stopped
                            # draining and this write sat in backpressure
                            # past the deadline.  Drop the stream — the
                            # cohort's seal window is shared state, so a
                            # reconnect with Last-Event-ID resumes with
                            # the delta chain it missed, on ANY process.
                            self.overload.note_eviction()
                            log.info(
                                "evicted slow SSE consumer (write blocked "
                                "> %gs); session %s resumes by event id",
                                write_deadline,
                                "anonymous" if not sid else sid[:8],
                            )
                            # abort, don't just return: aiohttp's
                            # finish_response awaits write_eof → drain,
                            # which waits on the SAME peer's backpressure
                            # with no timeout — without the abort the
                            # evicted socket, its buffered events, and
                            # this handler task would stay pinned until
                            # TCP teardown, re-creating the leak eviction
                            # exists to prevent
                            if request.transport is not None:
                                request.transport.abort()
                            evicted = True
                            break
                    else:
                        await write_buf(payload)
                if evicted:
                    break
                await asyncio.sleep(max(0.25, self.service.cfg.refresh_interval))
        except (*_CLIENT_GONE, asyncio.CancelledError):
            pass  # client went away — normal termination
        return resp

    async def export_csv(self, request: web.Request) -> web.Response:
        """The current wide per-chip table as CSV (one row per chip,
        identity columns + every metric column).  Always refreshes through
        the cache-gated frame path so the export is at most one refresh
        interval old, never an hours-stale snapshot."""
        frame = await self._get_frame(
            entry=self._entry(request),
            deadline=request.get("tpudash_deadline"),
        )
        stale = frame.get("error") or self.service.refresh_stalled
        if stale:
            # don't serve pre-outage (or mid-stall) data as if it were
            # current — a CSV has no warnings banner to carry the caveat
            raise web.HTTPServiceUnavailable(text=stale)
        df = self.service.last_df
        if df is None:
            raise web.HTTPServiceUnavailable(text="no frame rendered yet")
        # a 4096-chip table serializes for ~10ms — off the loop with the
        # rest of the frame machinery
        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(
            None, lambda: df.to_csv(index_label="chip")
        )
        return web.Response(
            text=text,
            content_type="text/csv",
            headers={
                "Content-Disposition": "attachment; filename=tpudash.csv"
            },
        )

    async def select(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text="invalid JSON") from e
        entry = self._entry(request)
        state = entry.state
        if not self.service.available:
            # No successful frame yet — prime one so selection ops
            # validate against a real chip list.
            await self._get_frame(force=True, entry=entry)
        available = self.service.available
        if body.get("all"):
            await self._mutate(entry, lambda: state.select_all(available))
        elif body.get("none"):
            await self._mutate(entry, state.clear)
        elif "toggle" in body:
            await self._mutate(
                entry, lambda: state.toggle(str(body["toggle"]), available)
            )
        elif "selected" in body:
            if not isinstance(body["selected"], list):
                raise web.HTTPBadRequest(text="'selected' must be a list")
            await self._mutate(
                entry,
                lambda: state.set_selected(
                    [str(k) for k in body["selected"]], available
                ),
            )
        else:
            raise web.HTTPBadRequest(text="no selection operation in body")
        # recompose this session's frame (data untouched: a selection
        # change must not trigger a re-scrape, the table didn't change)
        frame = await self._get_frame(
            entry=entry, deadline=request.get("tpudash_deadline")
        )
        self._publish_binding(request.cookies.get(SESSION_COOKIE), entry)
        return _json_response(
            {"selected": list(state.selected), "frame_ok": frame["error"] is None}
        )

    async def style(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text="invalid JSON") from e
        use_gauge = bool(body.get("use_gauge", True))
        entry = self._entry(request)

        def _set():
            entry.state.use_gauge = use_gauge

        await self._mutate(entry, _set)
        await self._get_frame(
            entry=entry, deadline=request.get("tpudash_deadline")
        )
        self._publish_binding(request.cookies.get(SESSION_COOKIE), entry)
        return _json_response({"use_gauge": entry.state.use_gauge})

    async def timings(self, request: web.Request) -> web.Response:
        """Stage-timing percentiles plus the overload layer's shed/evict
        counters — one stop for "is the serving side keeping up"."""
        summary = self.service.timer.summary()
        summary["overload"] = self.overload.snapshot()
        summary["loop_lag_ms"] = self.loop_monitor.summary()
        summary["census"] = process_census()
        # native-tier honesty: a deployment silently parsing in Python
        # (failed build/dlopen) must say so here, with the reason
        from tpudash import native as _native

        summary["native"] = _native.status()
        summary["broadcast"] = self.hub.stats()
        if self.bus_publisher is not None:
            summary["broadcast"]["bus"] = self.bus_publisher.stats()
        if self.service.tsdb is not None:
            # store counters (blocks/points/bytes/disk state); stats()
            # takes the store's sync lock, so it rides the executor
            loop = asyncio.get_running_loop()
            summary["tsdb"] = await loop.run_in_executor(
                None, self.service.tsdb.stats
            )
        if self.service.anomaly_engine is not None:
            # detection honesty: which scoring backend actually runs
            # (jax vs numpy fallback), per-tick score cost, baseline
            # coverage — stats() takes the baseline lock → executor
            loop = asyncio.get_running_loop()
            summary["anomaly"] = await loop.run_in_executor(
                None, self.service.anomaly_engine.stats
            )
        scatter_counters = getattr(
            self.service.source, "range_counters", None
        )
        if scatter_counters is not None:
            # the federated range plane's fan-in honesty: scatters,
            # per-child failures, replica serves, hedge wins
            summary["range_scatter"] = dict(scatter_counters)
        summary["range_cache_entries"] = len(self._range_cache)
        roster = getattr(self.service.source, "roster", None)
        if roster is not None:
            # fleet-membership truth (discovery/registration, PR 15):
            # raw pre-dwell entries with provenance and heartbeat age
            summary["federation_roster"] = roster.snapshot()
        summary["tier"] = self._tier_doc(summary.get("tsdb"))
        return _json_response(summary)

    def _tier_doc(self, tsdb_stats: "dict | None" = None) -> dict:
        """The process tier, observable in one key: supervised-child
        restart bookkeeping (worker mode) and the standby's replication
        lag (follower mode) — the numbers the crash-anything runbook
        alerts on."""
        tier: dict = {
            "mode": "single" if self.workers_provider is None else "workers",
            "restarts": 0,
        }
        if self.workers_provider is not None:
            wd = self.workers_provider()
            tier["restarts"] = wd.get("restarts", 0)
            tier["configured"] = wd.get("configured")
            bus = wd.get("bus") or {}
            tier["workers_connected"] = len(bus.get("workers") or [])
            children = wd.get("children")
            if children is None and isinstance(wd.get("supervisor"), dict):
                children = wd["supervisor"].get("children")
            if children:
                tier["children"] = children
        if tsdb_stats and tsdb_stats.get("replication"):
            rep = tsdb_stats["replication"]
            tier["replication_lag_s"] = rep.get("lag_s")
            tier["replication_caught_up"] = rep.get("caught_up")
        if tsdb_stats and tsdb_stats.get("cold"):
            c = tsdb_stats["cold"]
            tier["cold_bundles"] = c.get("bundles")
            tier["cold_unreachable"] = c.get("unreachable")
            tier["cold_quarantined"] = c.get("quarantined")
        return tier

    async def profile(self, request: web.Request) -> web.Response:
        """On-demand profiling (tracing, SURVEY.md §5 — the reference has
        none).  Two modes:

        - ``{"frames": N}`` (default 10, ≤100): cProfile N frame renders
          through the live service and return the hottest functions by
          cumulative time — works with every source;
        - ``{"device": true, "seconds": S}`` (≤30): capture a JAX device
          trace (TPU: XLA ops, ICI transfers; CPU: host trace) while the
          in-process probe/workload source keeps running; returns the
          trace directory for ``tensorboard --logdir`` / xprof.
        """
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text="invalid JSON") from e

        if body.get("device"):
            try:
                seconds = min(30.0, max(0.1, float(body.get("seconds", 3.0))))
            except (TypeError, ValueError) as e:
                raise web.HTTPBadRequest(
                    text="'seconds' must be a number"
                ) from e
            try:
                import jax  # the probe/workload sources already paid this
            except ImportError as e:
                raise web.HTTPBadRequest(text=f"jax unavailable: {e}") from e
            if self._device_trace_active:
                raise web.HTTPConflict(text="a device trace is already running")
            self._device_trace_active = True
            loop = asyncio.get_running_loop()
            try:
                trace_dir = await loop.run_in_executor(
                    None, lambda: tempfile.mkdtemp(prefix="tpudash-trace-")
                )
            except BaseException:  # incl. a cancelled handler
                self._device_trace_active = False
                raise

            def capture():
                with jax.profiler.trace(trace_dir):
                    # trace whatever the in-process source keeps the chip
                    # doing (workload steps / probes) for the window
                    time.sleep(seconds)

            try:
                await loop.run_in_executor(None, capture)
            except Exception as e:  # noqa: BLE001 — profiler errors → clean 500
                import shutil

                await loop.run_in_executor(
                    None, lambda: shutil.rmtree(trace_dir, ignore_errors=True)
                )
                raise web.HTTPInternalServerError(
                    text=f"device trace failed: {e}"
                ) from e
            finally:
                self._device_trace_active = False
            return _json_response(
                {"mode": "device", "seconds": seconds, "trace_dir": trace_dir}
            )

        try:
            frames = min(100, max(1, int(body.get("frames", 10))))
        except (TypeError, ValueError) as e:
            raise web.HTTPBadRequest(
                text="'frames' must be an integer"
            ) from e

        def run_profile():
            import cProfile
            import pstats

            # synthetic_load: profiled renders must not page anyone,
            # advance alert hysteresis, append to a recording, or inflate
            # source-health counters (tpudash.app.service.synthetic_load)
            deadline = time.monotonic() + 10.0  # bound lock-hold wall time
            done = 0
            prof = cProfile.Profile()
            with self.service.synthetic_load():
                prof.enable()
                try:
                    for _ in range(frames):
                        self.service.render_frame()
                        done += 1
                        if time.monotonic() >= deadline:
                            break
                finally:
                    prof.disable()
            stats = pstats.Stats(prof)
            top = []
            for func, (cc, nc, tt, ct, _callers) in stats.stats.items():
                filename, lineno, name = func
                top.append(
                    {
                        "function": f"{filename}:{lineno}({name})",
                        "calls": nc,
                        "tottime_ms": round(tt * 1e3, 3),
                        "cumtime_ms": round(ct * 1e3, 3),
                    }
                )
            top.sort(key=lambda e: -e["cumtime_ms"])
            return done, top[:40]

        async with self._lock:  # serialize against normal frame builds
            loop = asyncio.get_running_loop()
            t0 = time.monotonic()
            done, top = await loop.run_in_executor(None, run_profile)
            wall = time.monotonic() - t0
        return _json_response(
            {
                "mode": "frames",
                "frames": done,
                "requested": frames,
                "wall_ms": round(wall * 1e3, 2),
                "top": top,
            }
        )

    async def history(self, request: web.Request) -> web.Response:
        """Raw rolling history: fleet-average values per metric, or — with
        ``?chip=<key>`` — one chip's own series from the per-chip ring."""
        chip = request.query.get("chip")
        if chip is None:
            async with self._lock:  # render_frame appends from a worker
                snapshot = list(self.service.history)
            return _json_response(
                {
                    "history": [
                        {"ts": ts, "averages": avgs}
                        for ts, avgs in snapshot
                    ]
                }
            )
        # the chip path may decode compressed tsdb chunks (chip_series
        # takes the service's own lock internally) — executor, never
        # the event loop
        loop = asyncio.get_running_loop()
        series = await loop.run_in_executor(
            None, self.service.chip_series, chip
        )
        if series is None:
            raise web.HTTPNotFound(text=f"unknown chip {chip!r}")
        return _json_response(
            {
                "chip": chip,
                "history": [
                    {"ts": ts, "values": values} for ts, values in series
                ],
            }
        )

    def _range_params(self, request: web.Request) -> dict:
        """Parse/validate the shared ``/api/range`` param set (400 on
        malformed numbers)."""
        q = request.query

        def _num(name: str) -> "float | None":
            raw = q.get(name)
            if raw is None or raw == "":
                return None
            try:
                return float(raw)
            except ValueError:
                raise web.HTTPBadRequest(
                    text=f"{name} must be a number, not {raw!r}"
                ) from None

        cols_q = q.get("cols")
        return {
            "chip": q.get("chip") or None,
            "cols": (
                [c for c in cols_q.split(",") if c]
                if cols_q is not None
                else None
            ),
            "start": _num("start"),
            "end": _num("end"),
            "step": _num("step"),
            "agg": q.get("agg", "mean"),
            "points": _num("points"),
        }

    @staticmethod
    def _range_cache_key(query) -> str:
        """Canonical cache key for one range request: the known params
        only, sorted — cheap enough for the shed path (no parsing).
        ``merge`` is part of the key: a state-mode document and the
        finalized series for the same window are different bodies and
        must never share a cache entry or an ETag."""
        return "&".join(
            f"{k}={query[k]}"
            for k in (
                "chip", "cols", "start", "end", "step", "agg", "points",
                "merge",
            )
            if k in query and query[k] != ""
        )

    def _range_cache_put(
        self, key: str, etag: "str | None", body: bytes
    ) -> None:
        bound = getattr(self.service.cfg, "range_cache", 32)
        if bound <= 0:
            return
        cache = self._range_cache
        cache[key] = (etag, body)
        cache.move_to_end(key)
        while len(cache) > bound:
            cache.popitem(last=False)

    def _range_wire_params(self, p: dict) -> dict:
        """The param set forwarded to children on a scatter (the parent
        resolves nothing — each child picks its own tier and the state
        docs merge whatever comes back)."""
        return {
            "chip": p["chip"],
            "cols": ",".join(p["cols"]) if p["cols"] else None,
            "start": p["start"],
            "end": p["end"],
            "step": p["step"],
            "agg": p["agg"],
            "points": int(p["points"]) if p["points"] else None,
        }

    def _range_route(self, p: dict, state_mode: bool):
        """(scatter_fn, target_child, federated) for one query — the
        ONE routing decision (the ETag choice and the execution path
        both key off it).  On a fleet parent, fleet-scope queries and
        chip keys namespaced under a known child scatter (the child
        holds the real history; the parent's store only mirrors
        scraped latest values); ``__``-prefixed keys (the parent's own
        recording rules) and unknown keys stay local.  ``merge=state``
        always answers locally: it is the leaf protocol of the
        scatter, and a parent re-scattering it would make federation
        recursive (ROADMAP #3, not here)."""
        scatter = getattr(self.service.source, "scatter_range", None)
        if not callable(scatter) or state_mode:
            return None, None, False
        chip = p["chip"]
        if chip is None:
            return scatter, None, True
        if chip.startswith("__"):
            return scatter, None, False
        head = chip.split("/", 1)[0]
        if "/" in chip and head in self.service.source.child_urls():
            return scatter, head, True
        return scatter, None, False

    async def _range_result(
        self, request: web.Request, p: dict, route: tuple
    ) -> dict:
        """One finalized range answer (shared by the JSON and CSV
        routes): the local store for ordinary queries, the federated
        scatter-gather for fleet parents.  ``route`` is the
        _range_route triple the caller already resolved (the same one
        its ETag decision used).  Raises HTTP errors for the route to
        propagate."""
        svc = self.service
        loop = asyncio.get_running_loop()
        from tpudash.tsdb.query import DEFAULT_POINTS, MAX_POINTS
        max_points = (
            max(1, min(int(p["points"]), MAX_POINTS))
            if p["points"]
            else DEFAULT_POINTS
        )

        scatter, target_child, federated = route
        chip = p["chip"]
        fed_block = None
        if federated:
            wire_p = self._range_wire_params(p)
            if target_child is not None:
                wire_p["chip"] = chip.split("/", 1)[1]
            gathered = await loop.run_in_executor(
                None, lambda: scatter(wire_p, target_child)
            )
            from tpudash.analytics.executor import merge_states

            if gathered["states"]:
                try:
                    res = merge_states(
                        gathered["states"], p["agg"], max_points=max_points
                    )
                except ValueError as e:
                    raise web.HTTPBadRequest(text=str(e)) from e
                res["federation"] = {
                    "children": gathered["children"],
                    "partial": gathered["partial"],
                }
                res["partial"] = gathered["partial"]
                res["chip"] = chip or "fleet"
                return res
            # EMPTY gather (every child dark/version-skewed, e.g. a
            # rolling upgrade over pre-13 children): fall through to
            # the parent's OWN store — it mirrors the scraped fleet at
            # poll cadence, and a degraded local answer marked partial
            # beats the 503 the pre-13 parent never returned.  Only
            # when the local store has nothing either does this 503.
            fed_block = {
                "children": gathered["children"],
                "partial": True,
                "degraded": "local-mirror",
            }
            if svc.tsdb is None:
                detail = "; ".join(
                    f"{n}: {c.get('error', c['status'])}"
                    for n, c in gathered["children"].items()
                )
                raise web.HTTPServiceUnavailable(
                    text=f"no federated child answered the range query: "
                    f"{detail or 'no children configured'}"
                )

        if svc.tsdb is None:
            raise web.HTTPServiceUnavailable(text="trend store unavailable")
        from tpudash.tsdb import FLEET_SERIES
        from tpudash.tsdb.query import range_query

        key = chip if chip else FLEET_SERIES
        state_mode = request.query.get("merge") == "state"

        def run():
            tsdb = svc.tsdb
            if key != FLEET_SERIES and not tsdb.series_cols(key):
                return None  # no tier ever carried this series → 404
            if state_mode:
                from tpudash.analytics.executor import range_state

                return range_state(
                    tsdb,
                    chip,
                    p["cols"],
                    p["start"],
                    p["end"],
                    p["step"],
                    p["agg"],
                    max_points,
                )
            return range_query(
                tsdb,
                key,
                cols=p["cols"],
                start_s=p["start"],
                end_s=p["end"],
                step_s=p["step"],
                agg=p["agg"],
                max_points=max_points,
            )

        try:
            res = await loop.run_in_executor(None, run)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from e
        if res is None:
            if fed_block is not None:
                detail = "; ".join(
                    f"{n}: {c.get('error', c['status'])}"
                    for n, c in fed_block["children"].items()
                )
                raise web.HTTPServiceUnavailable(
                    text="no federated child answered the range query "
                    f"and the local mirror has no such series: {detail}"
                )
            raise web.HTTPNotFound(text=f"unknown series {chip!r}")
        if not state_mode:
            res["chip"] = chip or "fleet"
        if fed_block is not None:
            res["federation"] = fed_block
            res["partial"] = True
        return res

    async def range_api(self, request: web.Request) -> web.Response:
        """Long-horizon range query over the analytics plane
        (``tpudash.tsdb`` + ``tpudash.analytics``).  Query params, all
        optional:

        - ``chip=<slice>/<id>`` — one chip's series; omitted = the
          fleet scope (average row for mean/min/max, the fleet
          DISTRIBUTION for quantiles); ``__rule__/<name>`` = a
          recording-rule series
        - ``cols=a,b`` — column subset (default: every column the series
          carries)
        - ``start=<epoch_s>`` / ``end=<epoch_s>`` — window (default:
          newest sample back one hour)
        - ``step=<seconds>`` — alignment step; widened server-side when
          the point budget demands it
        - ``agg=mean|min|max|p50|p95|p99`` — bucket aggregate (default
          mean; quantiles answer from the sketch rollups)
        - ``points=<n>`` — point budget per column (ceiling 5000)
        - ``merge=state`` — the mergeable per-bucket aggregation state
          instead of finalized values (what a federation parent's
          scatter asks children for)

        On a federation parent, fleet-scope and child-namespaced
        queries scatter to the children under the per-child breaker/
        hedge/deadline machinery and merge exactly; the response then
        carries a ``federation`` block with per-child status/staleness
        and ``partial: true`` whenever any child didn't contribute
        fresh state — a dark child degrades the answer, never errors
        it.

        Revalidation: local answers carry an ETag keyed on (store
        version, params) — steady-state pollers pay 304, no executor
        hop.  Under overload the route degrades to its last cached
        body (``X-Tpudash-Stale: 1``) like ``/api/frame``.  400 on
        malformed params, 404 for a series no tier has ever carried."""
        svc = self.service
        p = self._range_params(request)
        cache_key = self._range_cache_key(request.query)
        state_mode = request.query.get("merge") == "state"
        route = self._range_route(p, state_mode)
        federated = route[2]
        etag = None
        if not federated and svc.tsdb is not None:
            digest = hashlib.sha1(
                f"{svc.tsdb.version}|{cache_key}".encode()
            ).hexdigest()[:16]
            etag = f'"rq-{digest}"'
            if request.headers.get("If-None-Match") == etag:
                return web.Response(
                    status=304,
                    headers={"Cache-Control": "no-cache", "ETag": etag},
                )
        res = await self._range_result(request, p, route)
        if not state_mode:
            # strict-JSON hygiene: a stored ±inf must not emit bare
            # Infinity
            res["series"] = {
                c: [
                    [ts, (v if -1e308 < v < 1e308 else None)]
                    for ts, v in pts
                ]
                for c, pts in res["series"].items()
            }
        body = _dumps(res).encode()
        self._range_cache_put(cache_key, etag, body)
        headers = {"Cache-Control": "no-cache"}
        if etag is not None:
            headers["ETag"] = etag
        return web.Response(
            body=body, content_type="application/json", headers=headers
        )

    async def range_csv(self, request: web.Request) -> web.Response:
        """``GET /api/range.csv`` — the same query surface, streamed as
        CSV (one row per timestamp, one column per metric; the
        ``/api/history.csv`` shape) for operators pulling incident
        evidence into a spreadsheet.  Federated queries export the
        merged fleet answer."""
        p = self._range_params(request)
        if request.query.get("merge") == "state":
            raise web.HTTPBadRequest(text="merge=state has no CSV form")
        res = await self._range_result(
            request, p, self._range_route(p, False)
        )
        from tpudash.analytics.executor import range_to_csv

        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(None, range_to_csv, res)
        name = "tpudash-range"
        if p["chip"]:
            name += "-" + p["chip"].replace("/", "_")
        return web.Response(
            text=text,
            content_type="text/csv",
            headers={
                "Content-Disposition": f"attachment; filename={name}.csv"
            },
        )

    async def chip(self, request: web.Request) -> web.Response:
        """Single-chip drill-down model (identity + gauges + chip trends +
        alerts + ICI neighbors) — reached by clicking a heatmap cell."""
        key = request.query.get("key")
        if not key:
            raise web.HTTPBadRequest(text="missing ?key=<slice>/<chip>")
        entry = self._entry(request)
        if self.service.last_df is None:
            await self._get_frame(entry=entry)  # prime on first request
        use_gauge = entry.state.use_gauge
        async with self._lock:
            # cheap membership gate BEFORE the cache and the executor: an
            # unknown-key probe loop must neither grow the cache nor
            # serialize figure builds behind the frame lock
            df = self.service.last_df
            if df is None or key not in df.index:
                raise web.HTTPNotFound(text=f"unknown chip {key!r}")
            # details change only when the data does: with N open drill
            # panels each SSE tick would otherwise rebuild ~10 figures per
            # panel under the frame lock, queueing every compose behind it
            cache_key = (key, use_gauge)
            version, cached = self._chip_cache
            if version == self._data_version and cache_key in cached:
                detail = cached[cache_key]
            else:
                loop = asyncio.get_running_loop()
                detail = await loop.run_in_executor(
                    None, self.service.chip_detail, key, use_gauge
                )
                if version != self._data_version or len(cached) > 2048:
                    cached = {}  # bound: ≤ 2 styles × chip count, reset
                cached[cache_key] = detail
                self._chip_cache = (self._data_version, cached)
        if detail is None:
            raise web.HTTPNotFound(text=f"unknown chip {key!r}")
        return _json_response(detail)

    async def alerts(self, request: web.Request) -> web.Response:
        """Current alert states (firing + pending), critical first."""
        async with self._lock:
            snapshot = list(self.service.last_alerts)
        return _json_response({"alerts": snapshot})

    async def incidents(self, request: web.Request) -> web.Response:
        """``GET /api/incidents`` — the incident timeline
        (tpudash.anomaly.timeline): alert state transitions and
        federation child-status flips stitched into ordered incident
        objects with stable ids and ``/api/range`` evidence links.

        Query params: ``limit`` (default 50), ``state=open|resolved``,
        ``since=<epoch_s>``.  Steady state is near-free: the ETag is the
        timeline's version counter, so a poller whose ``If-None-Match``
        still matches gets 304 with no body and no executor hop.
        Admitted under the OverloadGuard like every data route."""
        tl = self.service.timeline
        etag = f'"inc-{tl.version}"'
        headers = {"Cache-Control": "no-cache", "ETag": etag}
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304, headers=headers)
        q = request.query
        state = q.get("state")
        if state is not None and state not in ("open", "resolved"):
            raise web.HTTPBadRequest(
                text="state must be 'open' or 'resolved'"
            )
        try:
            limit = int(q.get("limit", "50"))
            since = float(q["since"]) if "since" in q else None
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from None
        # snapshot takes the timeline's sync lock and builds copies —
        # executor, never the event loop
        loop = asyncio.get_running_loop()
        doc = await loop.run_in_executor(
            None, lambda: tl.snapshot(limit=limit, state=state, since=since)
        )
        headers["ETag"] = f'"inc-{doc["version"]}"'
        return _json_response(doc, headers=headers)

    def _invalidate_frames(self) -> None:
        """Global-state change (silences): every session's cached compose
        is stale — bump all state versions (caller holds the lock), and
        bump the hub epoch so every cohort re-seals on its next tick."""
        self.sessions.invalidate_all()
        self.hub.invalidate()

    def _on_cohort_evict(self, cids) -> None:
        """Hub dropped cohorts (LRU or idle TTL): forget their publish
        cursors — the map must not outgrow the bounded cohort universe —
        and tell every bus mirror to drop the windows too."""
        for cid in cids:
            self._published_seqs.pop(cid, None)
        pub = self.bus_publisher
        if pub is not None:
            pub.publish_evict(list(cids))

    def _publish_seal(self, seal) -> None:
        """Hand a newly-created seal to the frame bus (worker mode); a
        tick that served a cached seal publishes nothing."""
        pub = self.bus_publisher
        if pub is None:
            return
        if self._published_seqs.get(seal.cid) == seal.seq:
            return
        self._published_seqs[seal.cid] = seal.seq
        pub.publish_seal(seal)

    def _publish_binding(self, sid: "str | None", entry: SessionEntry) -> None:
        """After a session mutation, tell the workers which cohort the
        session now composes into, so mid-stream selection changes take
        effect on the next worker tick.  Cookieless viewers share the
        default entry under the "" key — their selection changes must
        propagate too (the worker loop reads the same "" binding)."""
        pub = self.bus_publisher
        if pub is None:
            return
        cohort = self.hub.resolve(entry.state)
        pub.publish_binding(sid or "", cohort.cid)

    async def silence_alert(self, request: web.Request) -> web.Response:
        """POST {rule?, chip?, ttl_s} — acknowledge: silence matching
        alerts for ttl_s seconds (rule/chip default "*" wildcards).  The
        silence is flagged on frame/alert entries, excluded from webhook
        paging, persisted across restart, and expires on its own — when
        it does while the alert still fires, the pager fires then.

        A fleet-wide silence (both rule and chip wildcarded) mutes the
        entire pager, so it never happens by accident: at least one of
        rule/chip must be present in the body, or ``{"all": true}`` must
        opt in explicitly — an empty/malformed body is a 400, not a
        fleet-wide mute."""
        try:
            body = await request.json()
            ttl = float(body.get("ttl_s", 3600.0))
            rule = str(body.get("rule", "*") or "*")
            chip = str(body.get("chip", "*") or "*")
            # scope is judged on the EFFECTIVE values: {"rule": ""} or
            # {"rule": null} collapses to "*" and must not count as scoped
            if rule == "*" and chip == "*" and body.get("all") is not True:
                raise web.HTTPBadRequest(
                    text="refusing implicit fleet-wide silence: pass "
                    '"rule" and/or "chip", or {"all": true} to mute '
                    "everything on purpose"
                )
        except (ValueError, TypeError, AttributeError) as e:
            raise web.HTTPBadRequest(text=f"bad silence request: {e}") from e
        async with self._lock:
            try:
                entry = self.service.silences.add(rule, chip, ttl, time.time())  # tpulint: allow[wall-clock] silence expiries are epoch stamps
            except ValueError as e:
                raise web.HTTPBadRequest(text=str(e)) from e
            # re-annotate so the flag is live on the NEXT frame/alerts read,
            # not only after the next scrape cycle
            self.service.silences.annotate(self.service.last_alerts, time.time())  # tpulint: allow[wall-clock] silence expiries are epoch stamps
            await self._save_state()
            self._invalidate_frames()
        return _json_response({"silenced": entry})

    async def unsilence_alert(self, request: web.Request) -> web.Response:
        """POST {rule?, chip?} — drop the exact (rule, chip) silence."""
        try:
            body = await request.json()
            rule = str(body.get("rule", "*") or "*")
            chip = str(body.get("chip", "*") or "*")
        except (ValueError, TypeError, AttributeError) as e:
            raise web.HTTPBadRequest(text=f"bad unsilence request: {e}") from e
        async with self._lock:
            removed = self.service.silences.remove(rule, chip)
            self.service.silences.annotate(self.service.last_alerts, time.time())  # tpulint: allow[wall-clock] silence expiries are epoch stamps
            await self._save_state()
            self._invalidate_frames()
        if not removed:
            raise web.HTTPNotFound(text=f"no silence for {rule!r}/{chip!r}")
        return _json_response({"removed": {"rule": rule, "chip": chip}})

    async def list_silences(self, request: web.Request) -> web.Response:
        async with self._lock:
            active = self.service.silences.active(time.time())  # tpulint: allow[wall-clock] silence expiries are epoch stamps
        return _json_response({"silences": active})

    def _replay_source(self):
        """The FileReplaySource under the retry/recording wrappers, or
        None when the dashboard is not replaying a recording."""
        from tpudash.sources import unwrap_source
        from tpudash.sources.recorder import FileReplaySource

        return unwrap_source(self.service.source, FileReplaySource)

    async def replay_status(self, request: web.Request) -> web.Response:
        """Scrub-control state: current index/ts + recording bounds.
        404 when the active source is not a recording replay."""
        replay = self._replay_source()
        if replay is None:
            raise web.HTTPNotFound(text="not replaying a recording")
        async with self._lock:
            return _json_response(replay.position())

    async def replay_seek(self, request: web.Request) -> web.Response:
        """POST {index} | {t} | {paused} — time-travel an incident
        recording: seek to a snapshot (by index or recorded epoch
        timestamp), optionally pause auto-advance (scrub mode), and
        re-render immediately from the sought snapshot."""
        replay = self._replay_source()
        if replay is None:
            raise web.HTTPNotFound(text="not replaying a recording")
        # validate EVERYTHING before mutating anything: a 400 response
        # must not leave auto-advance silently paused
        try:
            body = await request.json()
            index = body.get("index")
            t = body.get("t")
            paused = body.get("paused")
            index = int(index) if index is not None else None
            t = float(t) if t is not None else None
        except (ValueError, TypeError, AttributeError) as e:
            raise web.HTTPBadRequest(text=f"bad replay request: {e}") from e
        async with self._lock:
            if paused is not None:
                replay.paused = bool(paused)
            if index is not None or t is not None:
                replay.seek(index=index, ts=t)
                # serve the sought snapshot NOW, not an interval later
                await self._refresh_locked(force=True)
            return _json_response(replay.position())

    async def stragglers(self, request: web.Request) -> web.Response:
        """Current fleet outliers (firing + pending), worst first — the
        chips gating SPMD lockstep, named (tpudash.stragglers)."""
        async with self._lock:
            snapshot = list(self.service.last_stragglers)
        return _json_response(
            {
                "stragglers": snapshot,
                "last_updated": self.service.last_updated,
            }
        )

    async def alert_rules_yaml(self, request: web.Request) -> web.Response:
        """The active alert rules as a Prometheus alerting-rule file, so
        the cluster pager can be configured from the same source of truth
        as the in-app banner (TPUDASH_ALERT_RULES)."""
        engine = self.service.alert_engine
        if engine is None:
            raise web.HTTPNotFound(
                text="alerting disabled (TPUDASH_ALERT_RULES=off)"
            )
        from tpudash.alerts import prometheus_rules_yaml

        text = prometheus_rules_yaml(
            engine.rules,
            self.service.cfg.refresh_interval,
            silences=self.service.silences.active(time.time()),  # tpulint: allow[wall-clock] silence expiries are epoch stamps
        )
        return web.Response(
            text=text,
            content_type="application/yaml",
            headers={
                "Content-Disposition": "attachment; filename=tpudash-alerts.yaml"
            },
        )

    async def schema(self, request: web.Request) -> web.Response:
        """Self-documenting API: every scraped series (with exporter help
        text), derived columns, panels, and generation registry — what a
        programmatic consumer needs to interpret /api/frame and the CSV."""
        from tpudash import compat
        from tpudash import schema as s
        from tpudash.app.service import _GENERIC_GAP, PANEL_GAP_REASONS
        from tpudash.registry import TPU_GENERATIONS

        df = self.service.last_df
        capabilities = {
            "source": self.service.source.name,
            # columns the ACTIVE source actually delivered last scrape
            # (None until the first successful frame)
            "available_columns": (
                sorted(map(str, df.columns)) if df is not None else None
            ),
            "panel_gaps": (
                [
                    {
                        "column": spec.column,
                        "title": spec.title,
                        "reason": PANEL_GAP_REASONS.get(
                            spec.column, _GENERIC_GAP
                        ),
                    }
                    for spec in s.PANELS
                    if df is not None and spec.column not in df.columns
                ]
            ),
            # standing dialect limitations, independent of the active source
            "dialect_notes": {
                col: reason for col, reason in PANEL_GAP_REASONS.items()
            },
        }
        return _json_response(
            {
                "capabilities": capabilities,
                "scrape_series": [
                    {"name": name, "help": s.SERIES_HELP.get(name, "")}
                    for name in (
                        *s.SCRAPE_SERIES, s.HBM_BANDWIDTH,
                        s.MXU_UTIL, s.MEMBW_UTIL,
                    )
                ],
                # real-world dialects accepted with zero config: GKE
                # tpu-device-plugin + libtpu runtime metric names
                "series_aliases": dict(sorted(compat.SERIES_ALIASES.items())),
                "derived_columns": list(s.DERIVED_COLUMNS),
                "identity_columns": list(s.IDENTITY_COLUMNS),
                "panels": [
                    {
                        "column": p.column,
                        "title": p.title,
                        "unit": p.unit,
                        "max_policy": p.max_policy,
                        "fixed_max": p.fixed_max,
                    }
                    for p in (*s.PANELS, *s.EXTRA_PANELS)
                ],
                # fleet outlier scoring (tpudash.stragglers): the active
                # watch list, or None when disabled
                "straggler_rules": (
                    [
                        {
                            "column": r.column,
                            "direction": r.direction,
                            "for_cycles": r.for_cycles,
                        }
                        for r in self.service.straggler_detector.rules
                    ]
                    if self.service.straggler_detector is not None
                    else None
                ),
                "generations": {
                    name: {
                        "hbm_gib": g.hbm_gib,
                        "nominal_power_w": g.nominal_power_w,
                        "peak_bf16_tflops": g.peak_bf16_tflops,
                        "ici_link_gbps": g.ici_link_gbps,
                        "accelerator_types": list(g.accelerator_types),
                    }
                    for name, g in TPU_GENERATIONS.items()
                },
            }
        )

    async def topology(self, request: web.Request) -> web.Response:
        """The fleet's torus model (dims, per-chip coordinates, ICI
        neighbor graph) for external tooling — the geometry the heatmaps
        render, as data."""
        entry = self._entry(request)
        if self.service.last_df is None:
            await self._get_frame(entry=entry)  # prime on first request
        loop = asyncio.get_running_loop()
        model = await loop.run_in_executor(None, self.service.topology_model)
        if model is None:
            raise web.HTTPServiceUnavailable(text="no frame rendered yet")
        return _json_response(model)

    async def config(self, request: web.Request) -> web.Response:
        """Effective configuration (secrets redacted) — "which knobs is
        this dashboard actually running with" without shell access to its
        pod.  Values come from the live Config, so env parsing and
        defaults are already applied."""
        import dataclasses

        cfg = dataclasses.asdict(self.service.cfg)
        for secret in ("auth_token", "alert_webhook"):
            if cfg.get(secret):
                cfg[secret] = "<set>"
        return _json_response({"config": cfg})

    async def history_csv(self, request: web.Request) -> web.Response:
        """The rolling trend history as CSV (one row per point, one column
        per metric) for offline analysis — fleet averages by default, one
        chip's own series with ``?chip=``."""
        chip = request.query.get("chip")
        if chip is None:
            async with self._lock:
                rows = [
                    (ts, dict(avgs)) for ts, avgs in self.service.history
                ]
        else:
            # chunk decode off the loop, same as the JSON history route
            loop = asyncio.get_running_loop()
            series = await loop.run_in_executor(
                None, self.service.chip_series, chip
            )
            if series is None:
                raise web.HTTPNotFound(text=f"unknown chip {chip!r}")
            rows = series
        columns: list = []
        for _, values in rows:
            for c in values:
                if c not in columns:
                    columns.append(c)
        lines = ["ts," + ",".join(columns)]
        for ts, values in rows:
            cells = [f"{ts:.3f}"]
            for c in columns:
                v = values.get(c)
                cells.append("" if v is None else f"{v}")
            lines.append(",".join(cells))
        name = f"tpudash-history{'-' + chip.replace('/', '_') if chip else ''}.csv"
        return web.Response(
            text="\n".join(lines) + "\n",
            content_type="text/csv",
            headers={"Content-Disposition": f"attachment; filename={name}"},
        )

    async def healthz(self, request: web.Request) -> web.Response:
        """Liveness + source health + overload state.  ``status``
        distinguishes "one slice quarantined" (degraded —
        source_health.endpoints names the open breaker) from "all sources
        down" (down) from "the SERVER is shedding load" (shedding/
        saturated — the source may be perfectly healthy; the serving side
        is protecting itself).  ``ok`` stays True throughout — the
        PROCESS is alive and serving, which is what a k8s liveness probe
        must measure (a restart fixes neither a down Prometheus nor a
        client swarm), and this route is exempt from admission control so
        liveness never flaps under load."""
        health = self.service.source_health()
        status = health.get("status") if health else None
        if status is None:
            status = "down" if self.service.last_error else "healthy"
        overload = self.overload.snapshot()
        if overload["state"] != "normal":
            # compose, don't replace: "degraded+shedding" tells the 3am
            # responder it's BOTH a source and a serving problem
            status = (
                overload["state"]
                if status == "healthy"
                else f"{status}+{overload['state']}"
            )
        doc = {"ok": True, "status": status,
               "source": self.service.source.name,
               "error": self.service.last_error,
               "overload": overload,
               "loop_lag_ms": self.loop_monitor.summary(),
               "census": process_census(),
               "source_health": health}
        if isinstance(health, dict) and health.get("federation"):
            # fleet parents surface per-child liveness top-level too —
            # the partition drill (and a paging runbook) reads child
            # status/staleness here without digging through source_health
            doc["federation"] = health["federation"]
        if self.workers_provider is not None:
            # worker-tier liveness folds in the same way overload does:
            # a mirror-less tier is serving NOBODY even though this
            # compose process is perfectly healthy
            wd = self.workers_provider()
            bus = wd.get("bus") or {}
            connected = len(bus.get("workers") or [])
            configured = int(wd.get("configured") or 0)
            doc["tier"] = {
                "mode": wd.get("mode", "workers"),
                "configured": configured,
                "workers_connected": connected,
                "restarts": wd.get("restarts", 0),
            }
            if configured and connected < configured:
                doc["status"] = status = (
                    "workers_down"
                    if status == "healthy"
                    else f"{status}+workers_down"
                )
        # follower (hot-standby) mode: replication state is a plain
        # attribute read — /healthz stays lock-free and never-shed
        rep = getattr(self.service.tsdb, "replication", None)
        if rep is not None:
            doc["replication"] = rep
        # cold archive tier: plain attribute reads only (same lock-free
        # contract).  A dark store degrades STATUS — range answers are
        # partial — but ``ok`` stays True: the process is alive and a
        # restart fixes nothing about an unreachable object store
        cold = getattr(self.service, "cold", None)
        if cold is not None:
            doc["cold"] = {
                "unreachable": cold.unreachable,
                "last_error": cold.last_error,
                "quarantined": cold.quarantined_count,
            }
            if cold.unreachable:
                doc["status"] = status = (
                    "cold_unreachable"
                    if status == "healthy"
                    else f"{status}+cold_unreachable"
                )
        return _json_response(doc)

    async def workers_api(self, request: web.Request) -> web.Response:
        """The broadcast plane's worker tier, observable: per-worker pids,
        bus backlog, and cohort-hub stats.  Single-process mode reports
        ``mode: "single"`` with just the hub."""
        import os

        doc = {
            "mode": "single",
            "compose_pid": os.getpid(),
            "broadcast": self.hub.stats(),
        }
        if self.workers_provider is not None:
            doc.update(self.workers_provider())
        return _json_response(doc)

    async def internal_cohort(self, request: web.Request) -> web.Response:
        """Worker-tier internal route (reachable only over the compose
        process's private unix socket): resolve a session id to its
        cohort, sealing the cohort's current frame so the worker's
        mirror has bytes to serve by the client's first event.  404 in
        single-process mode — the route has no business being public."""
        if self.bus_publisher is None:
            raise web.HTTPNotFound(text="no worker tier attached")
        sid = request.query.get("sid", "")
        entry = self.sessions.entry(sid or None)
        async with self._lock:
            await self._refresh_locked(False)
            cohort = self.hub.resolve(entry.state)
            seal = await self.hub.seal_cohort(cohort, self._tick_key())
            self._publish_seal(seal)
        self._publish_binding(sid, entry)
        return _json_response(
            {"sid": sid, "cid": cohort.cid, "seq": seal.seq}
        )

    def _sheddable_frame(self) -> "tuple[dict | None, tuple | None]":
        """The newest frame the shed path may degrade to, with its cache
        key.  Prefers the polling transport's last compose; a pure-SSE
        deployment (nothing ever hit ``/api/frame``) falls back to the
        newest cohort seal — keyed on (data_version, hub epoch), a
        2-part key distinguishable from the compose path's 3-part one,
        so the cached stale body still refreshes as data advances."""
        frame, key = self._last_frame, self._last_frame_key
        if frame is None and self.hub.last_frame is not None:
            return self.hub.last_frame, (self._data_version, self.hub.epoch)
        return frame, key

    async def _shed_response(
        self, request: web.Request, reason: str
    ) -> web.Response:
        """One shed request's response.  ``GET /api/frame`` degrades to
        the last published frame with a ``stale: true`` marker — a
        monitoring dashboard that answers "here is slightly-old data"
        beats one that answers 503 while the fleet burns.  Everything
        else sheds hard: 503 + Retry-After, constant-time, no executor —
        the whole point is that this path stays cheap at any request
        rate.  The one exception is the once-per-published-frame stale
        body build: serializing + gzipping ~100KB is loop-blocking work
        (asynccheck rule ``async-blocking``), so it runs in the executor
        behind a single-flight gate — a shed swarm arriving on a fresh
        frame dispatches one build and every later shed serves cached
        bytes with zero awaits."""
        headers = {"Retry-After": self.overload.retry_after_header()}
        if request.method == "GET" and request.path == "/api/summary":
            # a shed federation poll degrades to the cached summary the
            # same way /api/frame degrades: the parent marks staleness
            # from its own clock, so a slightly-old 200 (or a free 304 —
            # the common steady-state case) beats a 503 that would count
            # against this child's breaker while the fleet burns.
            # Served raw: the shed path short-circuits the _compress
            # middleware by design (constant-time, no executor).
            key, raw = self._summary_cache
            if raw is not None:
                etag = f'"s-{_key_id(key)}"'
                self.overload.note_stale_frame()
                headers["ETag"] = etag
                headers["Cache-Control"] = "no-cache"
                if request.headers.get("If-None-Match") == etag:
                    return web.Response(status=304, headers=headers)
                return web.Response(
                    body=raw,
                    content_type="application/json",
                    headers=headers,
                )
        if request.method == "GET" and request.path == "/api/range":
            # the analytics twin of the /api/frame degrade: a shed range
            # poll whose exact param set was answered recently serves
            # the cached body marked stale (header — the body bytes are
            # reused verbatim, serialization is exactly what the shed
            # path must not pay) instead of 503ing while the fleet
            # burns.  Cache key = canonical params; bounded LRU.
            hit = self._range_cache.get(self._range_cache_key(request.query))
            if hit is not None:
                etag, body = hit
                self.overload.note_stale_frame()
                headers["Cache-Control"] = "no-cache"
                headers["X-Tpudash-Stale"] = "1"
                if etag is not None:
                    stale_etag = f'{etag[:-1]}-stale"'
                    headers["ETag"] = stale_etag
                    if request.headers.get("If-None-Match") == stale_etag:
                        return web.Response(status=304, headers=headers)
                return web.Response(
                    body=body,
                    content_type="application/json",
                    headers=headers,
                )
        if request.method == "GET" and request.path == "/api/frame":
            frame, key = self._sheddable_frame()
            if frame is not None:
                # serialized (and gzipped) ONCE per published frame and
                # revalidated by ETag: a polling swarm being shed must
                # cost neither a fresh ~100KB _dumps() on the event loop
                # per request nor 100KB of uncompressed egress — the
                # shed path short-circuits the _compress middleware, so
                # it carries its own cached encoding
                etag = f'"{_key_id(key)}-stale"' if key is not None else None
                self.overload.note_stale_frame()
                if etag is not None:
                    headers["ETag"] = etag
                    if request.headers.get("If-None-Match") == etag:
                        return web.Response(
                            status=304,
                            headers={**headers, "Cache-Control": "no-cache"},
                        )
                if self._stale_body is None or self._stale_body[0] != key:
                    async with self._stale_build_lock:
                        # re-read under the gate: the frame may have
                        # advanced while this request queued — the build
                        # must target the NEWEST published frame, or a
                        # request holding a stale local key would
                        # overwrite a fresh cache and the next shed would
                        # rebuild it right back (ping-pong under the very
                        # swarm the single-flight gate exists for)
                        frame, key = self._sheddable_frame()
                        if (
                            self._stale_body is None
                            or self._stale_body[0] != key
                        ):
                            loop = asyncio.get_running_loop()
                            self._stale_body = await loop.run_in_executor(
                                None, _build_stale_body, key, frame
                            )
                # serve whatever the cache holds, with a MATCHING ETag —
                # the pre-lock etag may describe an older frame than the
                # body we are about to send
                body_key, raw, gz = self._stale_body
                if body_key is not None:
                    headers["ETag"] = f'"{_key_id(body_key)}-stale"'
                if _accepts_gzip(request.headers.get("Accept-Encoding", "")):
                    body = gz
                    headers["Content-Encoding"] = "gzip"
                else:
                    body = raw
                return web.Response(
                    body=body,
                    content_type="application/json",
                    headers={**headers, "Cache-Control": "no-cache"},
                )
        return _json_response(
            {"error": f"overloaded: shed ({reason})", "retry_after_s": self.overload.retry_after},
            status=503,
            headers=headers,
        )

    @web.middleware
    async def _admission(self, request: web.Request, handler):
        """Admission control (tpudash.app.overload): a global concurrency
        gate plus per-client token buckets, applied AFTER auth (shedding
        serves cached frame data on /api/frame — that must stay behind
        the bearer gate) and BEFORE any handler work.  /healthz, the
        static shell, and the vendored bundle are never shed.  Admitted
        requests carry a compute budget (``tpudash_deadline``) derived
        from the refresh watchdog, so a request that queues past its
        budget stops consuming refresh/compose time downstream."""
        path = request.path
        if (
            path in _NEVER_SHED
            or path == "/"
            or path == PLOTLY_LOCAL_URL
            or path.startswith("/internal/")
        ):
            # /internal/: worker-tier calls over the private unix socket —
            # the worker already admitted the client under ITS stream cap;
            # shedding here would double-count one client against two gates
            return await handler(request)
        guard = self.overload
        is_stream = path == "/api/stream"
        # streams hold their slot for minutes: they pass the rate bucket
        # here but are governed by max_streams, not the request gate
        reason = guard.admit(guard.client_key(request), gate=not is_stream)
        if reason is not None:
            return await self._shed_response(request, reason)
        watchdog = self.service.cfg.refresh_watchdog
        if watchdog and watchdog > 0:
            # 2×: the budget must outlive one full watchdog window that
            # STARTS mid-request (lock queueing first), or the stall
            # verdict would always lose the race to the request budget
            request["tpudash_deadline"] = time.monotonic() + 2.0 * watchdog
        try:
            return await handler(request)
        finally:
            if not is_stream:
                guard.release()

    @web.middleware
    async def _compress(self, request: web.Request, handler):
        """Negotiated gzip/deflate on sizable bodies: frame JSON is
        number-heavy and compresses ~6-8×, so a polling client's 100KB
        frame ships as ~15KB when the browser sends Accept-Encoding.
        Small bodies skip it (header overhead beats the win)."""
        resp = await handler(request)
        if (
            isinstance(resp, web.Response)
            and resp.body is not None
            and len(resp.body) > 1024
        ):
            resp.enable_compression()
        return resp

    @web.middleware
    async def _auth(self, request: web.Request, handler):
        """Bearer-token gate (Config.auth_token); only /api/stream also
        accepts ``?token=`` (EventSource transport).  /healthz stays open
        so Kubernetes probes don't need the secret, and the index page —
        a static shell with no metric data — stays open so a browser
        navigation (which cannot send headers) can load it; the page's
        JS then authenticates every data call.  The vendored plotly
        bundle is likewise public: a ``<script src>`` load cannot carry
        a header either, and the asset is a vendor library, not data."""
        token = self.service.cfg.auth_token
        if (
            request.path.startswith("/internal/")
            and self.bus_publisher is not None
        ):
            if self.bus_public and self.bus_token:
                # edge-tier mode: this compose is network-reachable, so
                # /internal/ trust cannot ride the transport — edges
                # (and hybrid-mode unix workers) authenticate with the
                # same bearer token their bus hello carries, checked
                # BEFORE the no-auth-token early return so an open
                # dashboard still has a closed internal plane.  An
                # empty bus token mirrors the publisher's own hello
                # policy: unauthenticated, for localhost-only setups.
                supplied = request.headers.get(BUS_TOKEN_HEADER, "")
                if not hmac.compare_digest(
                    supplied.encode(), self.bus_token.encode()
                ):
                    raise web.HTTPUnauthorized(
                        text="missing or invalid bus token"
                    )
                return await handler(request)
            # worker-tier internal calls arrive over the compose process's
            # private unix socket (never bound on TCP in worker mode) —
            # the WORKER enforces the bearer token for its local routes,
            # and proxied client requests still carry (and need) theirs
            return await handler(request)
        if not token or request.path in ("/", "/healthz", PLOTLY_LOCAL_URL):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        supplied = header[7:] if header.startswith("Bearer ") else None
        if supplied is None and request.path == "/api/stream":
            # EventSource cannot set headers, so /api/stream alone may pass
            # the token in the query string; every other route is
            # header-only (query strings leak into access logs, referrers,
            # and browser history)
            supplied = request.query.get("token")
        # compare as bytes: str compare_digest raises on non-ASCII input,
        # which would turn a bad token into a 500 instead of a 401
        if not supplied or not hmac.compare_digest(
            supplied.encode(), token.encode()
        ):
            raise web.HTTPUnauthorized(text="missing or invalid token")
        return await handler(request)

    def build_app(self) -> web.Application:
        app = web.Application(
            middlewares=[self._auth, self._admission, self._compress]
        )

        # deterministic thread footprint from the first request on: the
        # default executor's threads otherwise spawn lazily under load
        # and surface as census "growth" that is really cold start
        async def _warm_executor(app):
            await warm_default_executor()

        app.on_startup.append(_warm_executor)
        if self.service.cfg.loop_lag_budget > 0:
            # loop-lag sanitizer for the app's lifetime: callback timing
            # + stack attribution (install) and the heartbeat that feeds
            # the loop_lag_ms percentiles.  The task handle lives in app
            # storage — retained, cancellable at shutdown.
            async def _start_loopmon(app):
                self.loop_monitor.install()
                app[LOOPMON_TASK] = asyncio.create_task(
                    self.loop_monitor.run()
                )

            async def _stop_loopmon(app):
                task = app.get(LOOPMON_TASK)
                if task is not None:
                    task.cancel()
                    with contextlib.suppress(asyncio.CancelledError):
                        await task
                self.loop_monitor.uninstall()

            app.on_startup.append(_start_loopmon)
            app.on_cleanup.append(_stop_loopmon)
        app.router.add_get("/", self.index)
        app.router.add_get("/api/frame", self.frame)
        app.router.add_get("/api/summary", self.summary)
        app.router.add_get("/api/child/{child}/{tail:.+}", self.child_proxy)
        app.router.add_post(
            "/api/federation/register", self.federation_register
        )
        app.router.add_get("/api/stream", self.stream)
        app.router.add_get("/api/export.csv", self.export_csv)
        app.router.add_post("/api/select", self.select)
        app.router.add_post("/api/style", self.style)
        app.router.add_get("/api/timings", self.timings)
        app.router.add_get("/api/schema", self.schema)
        app.router.add_post("/api/profile", self.profile)
        app.router.add_get("/api/history", self.history)
        app.router.add_get("/api/history.csv", self.history_csv)
        app.router.add_get("/api/range", self.range_api)
        app.router.add_get("/api/range.csv", self.range_csv)
        app.router.add_get("/api/chip", self.chip)
        app.router.add_get("/api/config", self.config)
        app.router.add_get("/api/topology", self.topology)
        app.router.add_get("/api/alerts", self.alerts)
        app.router.add_get("/api/incidents", self.incidents)
        app.router.add_post("/api/alerts/silence", self.silence_alert)
        app.router.add_post("/api/alerts/unsilence", self.unsilence_alert)
        app.router.add_get("/api/alerts/silences", self.list_silences)
        app.router.add_get("/api/stragglers", self.stragglers)
        app.router.add_get("/api/workers", self.workers_api)
        app.router.add_get("/internal/cohort", self.internal_cohort)
        app.router.add_get("/api/replay", self.replay_status)
        app.router.add_post("/api/replay", self.replay_seek)
        app.router.add_get("/api/alert-rules.yaml", self.alert_rules_yaml)
        app.router.add_get("/healthz", self.healthz)
        app.router.add_get(PLOTLY_LOCAL_URL, self.plotly_asset)
        async def _close_child_session(app):
            if self._child_session is not None:
                await self._child_session.close()
                self._child_session = None

        app.on_cleanup.append(_close_child_session)
        if self.service.announcer is not None:
            # stop the announce heartbeat (the join may block on a
            # parked POST for its timeout — executor, never the loop)
            async def _close_announcer(app):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(
                    None, self.service.close_announcer
                )

            app.on_cleanup.append(_close_announcer)
        if self.service.cfg.history_path:
            # final trend snapshot on graceful shutdown (periodic saves
            # cover crashes up to history_save_interval behind)
            async def _save_history(app):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, self.service.save_history)

            app.on_cleanup.append(_save_history)
        if self.service.cfg.state_path:
            # final state snapshot (sessions idle since their last
            # mutation would otherwise persist stale idle ages)
            async def _save_state_on_exit(app):
                await self._save_state()

            app.on_cleanup.append(_save_state_on_exit)
        if self.service.tsdb is not None:
            # graceful shutdown seals the tsdb's partial head chunk (a
            # crash loses only that head — the drill asserts it); the
            # seal encodes + fsyncs, so it rides the executor
            async def _close_tsdb(app):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, self.service.close_tsdb)

            app.on_cleanup.append(_close_tsdb)
        if self.service.anomaly_engine is not None:
            # graceful shutdown persists the seasonal baselines beside
            # the tsdb segments (npz write → executor, never the loop)
            async def _close_analysis(app):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, self.service.close_analysis)

            app.on_cleanup.append(_close_analysis)
        return app


def make_app(cfg: Config | None = None) -> web.Application:
    cfg = cfg or load_config()
    service = DashboardService(cfg, make_source(cfg))
    server = DashboardServer(service)
    app = server.build_app()
    if cfg.workers == 0 and cfg.bus_listen:
        # single-process compose fronted by an edge tier: publish the
        # frame bus over TCP/TLS beside the normal local serving
        from tpudash.broadcast.supervisor import attach_network_bus

        attach_network_bus(cfg, server, app)
    return app


def run(cfg: Config | None = None) -> None:  # pragma: no cover - blocking entry
    from tpudash.config import configure_logging
    from tpudash.parallel.distributed import maybe_initialize

    configure_logging()
    # multi-host rendezvous must precede any device query; also covers
    # the installed `tpudash` console script, not just `python -m`
    maybe_initialize()
    cfg = cfg or load_config()
    if cfg.workers > 0:
        # TPUDASH_WORKERS mode: one compose process publishing sealed
        # cohort buffers on a frame bus + N SO_REUSEPORT worker processes
        # serving clients from bus mirrors.  Preflights fail fast (no
        # silent single-process fallback).
        from tpudash.broadcast.supervisor import run_supervised

        run_supervised(cfg)
        return
    web.run_app(make_app(cfg), host=cfg.host, port=cfg.port)
