"""TDB1 — the compact binary delta/frame wire format.

BENCH_r05's two scale walls are JSON-shaped: a steady-state SSE delta at
4,096 chips is ~344 KB, almost all of it heatmap z-matrices and the
per-host breakdown re-shipping full metric names and decimal text every
tick.  This module encodes those bulk numerics in a versioned
little-endian binary layout; everything small (timings, alerts, stats,
figure-value patches, trends) rides verbatim in a compact JSON "head",
so the format never re-implements frame semantics — it is a CONTAINER
around the existing delta contract (tpudash/app/delta.py).

The decoder is ``tpudash/app/clientlogic.py`` (``decode_bin_sections``
and friends): ONE implementation executed by the Python test suite and
transpiled into the served page, so the browser's binary path can never
drift from the server's (same single-source scheme as apply_delta).
This module is the encoder plus the container framing, and it derives
temporal-delta bases through the very same ``qd_base`` the decoder uses.

Byte layout (all integers little-endian)::

    0   4   magic  b"TDB1"
    4   1   version (1)
    5   1   kind: 1 = delta, 3 = summary, 4 = figure template,
              5 = cfull (columnar full), 6 = full-frame envelope
    6   2   reserved (0)
    8   4   head_len (u32)
    12  N   head: compact JSON (UTF-8)
    .   4   payload_len (u32)
    .   M   payload: the binary sections, in head-descriptor order

The head is the frame/delta dict with the bulk fields removed and a
``_b`` descriptor added::

    _b.hm  = {"shapes": [[rows, cols], ...], "changed": [0|1, ...]}
    _b.bd  = [[dim, [row names...], [value columns...]], ...]
    _b.ch  = {"n": chips, "slices": [...], "hosts": [...],
              "models": [...]}                  (kind=template only)
    _b.sel = selected count                     (kind=template only)
    _b.tg / _b.cs = interned hover-text / colorscale grids (JSON)
    _b.cg  = [[rows, cols], ...] customdata grid shapes (sections)

Sections follow in a fixed order: changed heatmap grids (row-major
cells), breakdown dims (per row: presence bitmask varint, chip-count
varint, one value per present column); a TEMPLATE's sections are the
columnar chip table (interned slice/host/model codes, delta-coded chip
ids, a selected bitmap), the selection as zigzag delta-coded chip
indices, and the customdata grids as varint chip-table references.

Columnar full frames (PR 11) split a frame into the figure-structure
TEMPLATE — everything a delta cannot change, (re)built exactly when
``frame_delta`` returns None and sent once per cohort template epoch —
and a per-tick CFULL carrying scalar fields verbatim plus
self-contained z/breakdown sections, referencing its template by id.
A cfull against the wrong template REFUSES (both ends), so numeric
sections are never reassembled onto stale structure.  Kind 6 is the
self-contained envelope (template + cfull concatenated) that binary
``/api/frame`` serves.  The old kind 2 (inline-figure full frame) is
retired; a kind-2 document refuses loudly.

Every cell value is one *quantized* varint (``qv``): code 0 = null,
1 = raw float64 escape (8 bytes), 2/3 = ±inf, 4 = NaN, and ≥5 a zigzag
scaled-centi delta against the same cell of the PREVIOUS frame (both
ends hold it — that is the delta contract).  Frame values are already
display-rounded to 2 decimals by compose, so the common cell is 1-2
bytes; any value outside the exact centi-integer envelope escapes to
raw float64, keeping the codec lossless (−0.0 included; NaN decodes to
the canonical quiet NaN on both ends, which is as bit-exact as a JS
Number can represent one).
"""

from __future__ import annotations

import json
import math
import struct

from tpudash import wireids
from tpudash.app import clientlogic
from tpudash.app.delta import (
    SCALAR_FIELDS,
    _signature,
    frame_delta,
    frame_patch,
)

MAGIC = wireids.TDB1_MAGIC
VERSION = wireids.TDB1_VERSION
KIND_DELTA = wireids.TDB1_KIND_DELTA
KIND_SUMMARY = wireids.TDB1_KIND_SUMMARY
#: columnar full-frame trio (PR 11): the figure STRUCTURE — figure
#: dicts, interned hover-text/customdata/colorscale grids, the columnar
#: chip table, the selection — is a TEMPLATE sent once per cohort
#: template epoch (kind 4); each tick's numeric sections ride a CFULL
#: (kind 5) that references its template by id; kind 6 is the
#: self-contained envelope (template + cfull concatenated) that
#: ``/api/frame`` serves.  The old kind 2 (full frame with inline
#: figure JSON) is retired — a kind-2 document now refuses loudly.
KIND_TEMPLATE = wireids.TDB1_KIND_TEMPLATE
KIND_CFULL = wireids.TDB1_KIND_CFULL
KIND_FULLC = wireids.TDB1_KIND_FULLC
#: incremental summary (PR 15): the per-chip matrix as a changed-cell
#: bitmap + qv cells against the PARENT'S LAST-ACKED summary (named by
#: its ETag in the head descriptor); identity/keys/cols are elided —
#: the base document carries them, and a child falls back to the full
#: kind-3 document unconditionally whenever identity changed or the
#: advertised base is one it no longer holds
KIND_SUMMARY_DELTA = wireids.TDB1_KIND_SUMMARY_DELTA

#: negotiated content type for binary frames/deltas
CONTENT_TYPE = "application/x-tpudash-bin"
#: the binary stream's content type (``/api/stream?format=bin``)
STREAM_CONTENT_TYPE = "application/x-tpudash-stream"

#: binary stream event types (the SSE analog: full / delta / keepalive,
#: plus the figure-structure template that must precede any columnar
#: full event whose template the client does not already hold)
EVT_FULL = wireids.TE_EVT_FULL
EVT_DELTA = wireids.TE_EVT_DELTA
EVT_KEEPALIVE = wireids.TE_EVT_KEEPALIVE
EVT_TEMPLATE = wireids.TE_EVT_TEMPLATE


def bin_event(etype: int, event_id: str, body: bytes) -> bytes:
    """One framed binary stream event: ``b"TE" | u8 type | u8 id_len |
    id (ASCII) | u32 body_len | body``.  Event ids are the same
    ``<cohort>-<seq>`` strings the SSE path uses, so ``?last_id=``
    resume rides the existing seal-window machinery unchanged."""
    ib = event_id.encode("ascii")
    if len(ib) > 255:
        raise WireError("event id too long")
    return (
        b"TE" + bytes((etype, len(ib))) + ib
        + struct.pack("<I", len(body)) + body
    )


def split_bin_events(buf: bytes):
    """(events, remainder): parse complete framed events off the front
    of ``buf`` — the client-side splitter (tests and tooling; the page's
    hand-JS splitter mirrors this layout)."""
    out = []
    pos = 0
    while True:
        if len(buf) - pos < 8:
            break
        if buf[pos : pos + 2] != b"TE":
            raise WireError("bad stream framing")
        etype = buf[pos + 2]
        idlen = buf[pos + 3]
        hdr_end = pos + 4 + idlen
        if hdr_end + 4 > len(buf):
            break
        try:
            event_id = buf[pos + 4 : hdr_end].decode("ascii")
        except UnicodeDecodeError as e:
            raise WireError(f"non-ASCII stream event id: {e!r}") from e
        blen = int.from_bytes(buf[hdr_end : hdr_end + 4], "little")
        end = hdr_end + 4 + blen
        if end > len(buf):
            break
        out.append((etype, event_id, buf[hdr_end + 4 : end]))
        pos = end
    return out, buf[pos:]

_dumps = json.dumps


class WireError(ValueError):
    """Malformed/unsupported TDB1 document — callers fall back to JSON."""


def _wv(out: bytearray, v: int) -> None:
    """LEB128 varint append (values < 2^53 by construction)."""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _qv(out: bytearray, v, base100) -> None:
    """One quantized cell (see module doc).  ``base100`` comes from
    clientlogic.qd_base over the previous frame's cell, so encoder and
    decoder anchor on identical integers by construction."""
    if v is None:
        out.append(0)
        return
    v = float(v)
    if math.isnan(v):
        out.append(4)
        return
    if math.isinf(v):
        out.append(2 if v > 0 else 3)
        return
    if v == 0.0 and math.copysign(1.0, v) < 0:
        # -0.0 must survive bit-exactly: the scaled path would decode +0.0
        out.append(1)
        out += struct.pack("<d", v)
        return
    small = abs(v) < (1 << 52) / 100.0  # round(v*100) must not overflow
    v100 = round(v * 100) if small else 0
    if small and -(1 << 52) < v100 < (1 << 52) and v100 / 100.0 == v:
        d = v100 - int(base100)
        if -(1 << 51) < d < (1 << 51):
            z = (d << 1) ^ (d >> 63)  # zigzag
            _wv(out, z + 5)
            return
    out.append(1)
    out += struct.pack("<d", v)


def _cell_base(prev_cell) -> int:
    b = clientlogic.qd_base(prev_cell)
    # qd_base returns a float in the exact-integer range (or 0)
    return int(b)


def _prev_z(prev: "dict | None", i: int):
    if not prev:
        return None
    hms = prev.get("heatmaps")
    if not hms or i >= len(hms):
        return None
    return hms[i]["figure"]["data"][0]["z"]


def _encode_heatmaps(delta: dict, prev: "dict | None", head_b: dict,
                     out: bytearray) -> None:
    zs = delta["heatmaps"]
    shapes = []
    changed = []
    for i, z in enumerate(zs):
        rows = len(z)
        cols = len(z[0]) if rows else 0
        shapes.append([rows, cols])
        pz = _prev_z(prev, i)
        if pz == z:
            changed.append(0)
            continue
        changed.append(1)
        vals = [v for zr in z for v in zr]
        if pz is not None and len(pz) == rows and all(
            len(pr) == cols for pr in pz
        ):
            bases = [v for pr in pz for v in pr]
        else:
            bases = [float("nan")] * (rows * cols)  # NaN prev → base 0
        _qv_stream(out, vals, bases)
    head_b["hm"] = {"shapes": shapes, "changed": changed}


def _encode_breakdown(delta: dict, prev: "dict | None", head_b: dict,
                      out: bytearray) -> None:
    """Stream-separated per-dim layout (masks, then chip counts, then
    the value cells) so the value cells form ONE contiguous qv stream
    the native bulk encoder can emit in a single call."""
    bd = delta["breakdown"]
    pbd = (prev or {}).get("breakdown") or {}
    dims_desc = []
    nan = float("nan")
    for dim, rows in bd.items():
        names = list(rows.keys())
        cols: list = []
        seen = set()
        for row in rows.values():
            for c in row:
                if c != "chips" and c not in seen:
                    seen.add(c)
                    cols.append(c)
        if len(cols) > 52:  # presence bitmask must stay an exact float
            raise WireError(f"breakdown dim {dim!r} has {len(cols)} columns")
        dims_desc.append([dim, names, cols])
        pdim = pbd.get(dim) or {}
        vals: list = []
        bases: list = []
        for name in names:
            row = rows[name]
            prow = pdim.get(name) or {}
            mask = 0
            for k, c in enumerate(cols):
                if c in row:
                    mask |= 1 << k
                    vals.append(row[c])
                    bases.append(prow.get(c, nan))
            _wv(out, mask)
        for name in names:
            _wv(out, int(rows[name].get("chips", 0)))
        _qv_stream(out, vals, bases)
    head_b["bd"] = dims_desc


def _qv_stream(out: bytearray, vals: list, prev_vals) -> None:
    """Append one qv cell per value, anchored on the matching previous
    value (None/NaN prev → base 0).  Routed through the native bulk
    encoder when available and the values are all numeric; the Python
    loop below is the always-correct fallback (and the None-carrying
    path — nulls only occur in object-shaped heatmap rows)."""
    import numpy as np

    from tpudash import native

    # None cells (heatmap gaps) must encode as code-0 null, and numpy
    # would silently coerce them to NaN (np.asarray(None, float) → nan,
    # no exception) — so the native path is gated on an explicit scan
    if (
        native.is_available()
        and len(vals) >= 32
        and None not in vals
        and None not in prev_vals
    ):
        try:
            v = np.asarray(vals, dtype=np.float64)
            p = np.asarray(prev_vals, dtype=np.float64)
        except (TypeError, ValueError):
            v = None
        if v is not None and v.shape == p.shape:
            out += native.qv_encode_block(v, p)
            return
    for val, pv in zip(vals, prev_vals):
        _qv(out, val, _cell_base(None if pv is None else pv))


def _pack_str_table(values) -> "tuple[list, list]":
    """(uniques, codes) — first-seen-order interning for the columnar
    chip table."""
    memo: dict = {}
    uniq: list = []
    codes: list = []
    for v in values:
        c = memo.get(v)
        if c is None:
            c = memo[v] = len(uniq)
            uniq.append(v)
        codes.append(c)
    return uniq, codes


def _encode_chips(frame: dict, head_b: dict, out: bytearray) -> None:
    """Columnar chip table for FULL frames: interned identity columns,
    delta-coded chip ids, selected bitmap.  Keys are derived
    ("<slice>/<chip_id>"), so they never ride the wire."""
    chips = frame["chips"]
    slice_u, slice_c = _pack_str_table(c["slice"] for c in chips)
    host_u, host_c = _pack_str_table(c["host"] for c in chips)
    model_u, model_c = _pack_str_table(c["model"] for c in chips)
    head_b["ch"] = {
        "n": len(chips),
        "slices": slice_u,
        "hosts": host_u,
        "models": model_u,
    }
    prev_id = 0
    for i, c in enumerate(chips):
        _wv(out, slice_c[i])
        _wv(out, host_c[i])
        _wv(out, model_c[i])
        d = int(c["chip_id"]) - prev_id
        prev_id = int(c["chip_id"])
        _wv(out, ((d << 1) ^ (d >> 63)))  # zigzag: ids ascend per slice
    # selected bitmap, 8 chips per byte, LSB first
    acc = 0
    nbits = 0
    for c in chips:
        acc |= (1 if c.get("selected") else 0) << nbits
        nbits += 1
        if nbits == 8:
            out.append(acc)
            acc = 0
            nbits = 0
    if nbits:
        out.append(acc)


def _container(kind: int, head: dict, payload: bytes) -> bytes:
    hb = _dumps(head, separators=(",", ":")).encode()
    return (
        MAGIC
        + bytes((VERSION, kind, 0, 0))
        + struct.pack("<I", len(hb))
        + hb
        + struct.pack("<I", len(payload))
        + payload
    )


def split_container(buf: bytes) -> "tuple[int, dict, bytes]":
    """(kind, head, payload) of a TDB1 document, or WireError."""
    if len(buf) < 12 or buf[:4] != MAGIC:
        raise WireError("not a TDB1 document")
    if buf[4] != VERSION:
        raise WireError(f"unsupported TDB1 version {buf[4]}")
    kind = buf[5]
    head_len = int.from_bytes(buf[8:12], "little")
    head_end = 12 + head_len
    if head_end + 4 > len(buf):
        raise WireError("truncated TDB1 head")
    try:
        head = json.loads(buf[12:head_end])
    except ValueError as e:
        raise WireError(f"bad TDB1 head: {e}") from e
    if not isinstance(head, dict):
        raise WireError("TDB1 head is not an object")
    pay_len = int.from_bytes(buf[head_end : head_end + 4], "little")
    payload = buf[head_end + 4 : head_end + 4 + pay_len]
    if len(payload) != pay_len:
        raise WireError("truncated TDB1 payload")
    return kind, head, payload


#: delta fields that carry bulk numerics into binary sections; every
#: other field rides the JSON head verbatim
_BULK_DELTA_FIELDS = ("heatmaps", "breakdown")


def encode_delta(prev: "dict | None", delta: "dict | None") -> "bytes | None":
    """The binary twin of one JSON delta (None in → None out, mirroring
    frame_delta's structural-change contract)."""
    if delta is None:
        return None
    head = {k: v for k, v in delta.items() if k not in _BULK_DELTA_FIELDS}
    head_b: dict = {}
    out = bytearray()
    if "heatmaps" in delta:
        _encode_heatmaps(delta, prev, head_b, out)
    if "breakdown" in delta:
        _encode_breakdown(delta, prev, head_b, out)
    head["_b"] = head_b
    return _container(KIND_DELTA, head, bytes(out))


def decode_delta(buf: bytes, prev: "dict | None") -> dict:
    """Python-side decode — a thin wrapper over the clientlogic decoder
    (the SAME code the page runs), so tests and server-side consumers
    share one implementation with the browser."""
    kind, head, payload = split_container(buf)
    if kind != KIND_DELTA:
        raise WireError(f"expected a delta container, got kind {kind}")
    try:
        return clientlogic.decode_bin_sections(head, payload, prev or {})
    except WireError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, struct.error) as e:
        # the shared browser decoder assumes a coherent document; a
        # malformed one must refuse at THIS boundary, not escape its
        # internals' exceptions past callers catching WireError
        raise WireError(f"malformed delta sections: {e!r}") from e


#: the structural half of a frame — everything the TEMPLATE carries and
#: the cfull must NOT re-ship (figure value patches replace the last
#: four at apply time; every field outside this set and SCALAR_FIELDS
#: rides the cfull head verbatim, so per-tick additions like the
#: federation block stay current on the columnar path)
_TEMPLATE_FIELDS = (
    "error",
    "use_gauge",
    "refresh_interval",
    "panel_specs",
    "selected",
    "chips",
    "average",
    "device_rows",
    "heatmaps",
    "trends",
)


def _intern(value, memo: dict, uniq: list) -> int:
    """Grid interning for the template head: heatmap figures of one
    slice share their hover-text/customdata/colorscale grids, so 96
    panel figures reference ~16 entries instead of re-shipping ~520 KB
    of repeated JSON.  Keyed by serialized content (live frames share
    grid OBJECTS per slice, but JSON-domain copies do not)."""
    key = _dumps(value)
    idx = memo.get(key)
    if idx is None:
        idx = memo[key] = len(uniq)
        uniq.append(value)
    return idx


def encode_template(frame: dict, tid: str) -> bytes:
    """The figure-structure TEMPLATE (kind 4) of one frame: the exact
    structural half a delta cannot change — sent once per cohort
    template epoch (the template is (re)built precisely when
    ``frame_delta`` returns None, so it is valid along every delta
    chain that follows it).  Raises WireError on any frame shape the
    patch protocol cannot reconstruct (error frames, unknown figure
    types) — callers fall back to the JSON full frame."""
    if _signature(frame) is None:
        raise WireError("frame shape is not template-encodable")
    # WHITELIST copy: only the structural fields the signature pins may
    # live in the template.  Copying "everything non-scalar" would bake
    # per-tick extras (federation block, partial/stale markers) into
    # the epoch — and since a cfull can only add fields, an extra that
    # later DISAPPEARS from the frame would persist stale in every
    # reconstruction until the next structural break.  Whitelisted
    # fields are exactly the ones apply_delta patches or the signature
    # freezes; everything else rides each cfull verbatim.
    head = {
        k: frame[k]
        for k in _TEMPLATE_FIELDS
        if k in frame and k not in ("chips", "selected", "heatmaps")
    }
    head["tid"] = tid
    head_b: dict = {}
    out = bytearray()
    chips = frame.get("chips")
    chip_index: dict = {}
    if chips is None:
        if "chips" in frame:
            head["chips"] = None
    else:
        _encode_chips(frame, head_b, out)
        chip_index = {c["key"]: i for i, c in enumerate(chips)}
        sel = frame.get("selected")
        if sel is None:
            if "selected" in frame:
                head["selected"] = None
        else:
            # selection as zigzag delta-coded chip indices (sorted
            # selections delta to 1 byte per chip; any order round-trips)
            head_b["sel"] = len(sel)
            prev = 0
            for key in sel:
                i = chip_index.get(key)
                if i is None:
                    raise WireError(f"selected key {key!r} not in chip table")
                d = i - prev
                _wv(out, ((d << 1) ^ (d >> 63)))
                prev = i
    hms = frame.get("heatmaps")
    if hms is None:
        if "heatmaps" in frame:
            head["heatmaps"] = None
    else:
        tg: list = []
        tg_memo: dict = {}
        cs: list = []
        cs_memo: dict = {}
        cg_grids: list = []
        cg_memo: dict = {}
        out_hm = []
        for hm in hms:
            fig = hm["figure"]
            trace = dict(fig["data"][0])
            trace.pop("z", None)
            if "text" in trace:
                trace["text"] = _intern(trace["text"], tg_memo, tg)
            if "colorscale" in trace:
                trace["colorscale"] = _intern(
                    trace["colorscale"], cs_memo, cs
                )
            if "customdata" in trace:
                trace["customdata"] = _intern(
                    trace["customdata"], cg_memo, cg_grids
                )
            out_hm.append(
                {**hm, "figure": {**fig, "data": [trace, *fig["data"][1:]]}}
            )
        head["heatmaps"] = out_hm
        head_b["tg"] = tg
        head_b["cs"] = cs
        if cg_grids:
            # customdata cells are chip keys: encode each grid as varint
            # chip-table references (0 = torus padding) — the decoder
            # rebuilds the key strings from the columnar chip table
            shapes = []
            for grid in cg_grids:
                rows = len(grid)
                cols = len(grid[0]) if rows else 0
                if any(len(row) != cols for row in grid):
                    raise WireError("ragged customdata grid")
                shapes.append([rows, cols])
                for row in grid:
                    for cell in row:
                        if cell is None:
                            _wv(out, 0)
                            continue
                        i = chip_index.get(cell)
                        if i is None:
                            raise WireError(
                                f"customdata key {cell!r} not in chip table"
                            )
                        _wv(out, i + 1)
            head_b["cg"] = shapes
    head["_b"] = head_b
    return _container(KIND_TEMPLATE, head, bytes(out))


def encode_cfull(frame: dict, tid: str) -> bytes:
    """The per-tick numeric half (kind 5): every scalar field and any
    non-structural extra (federation block, stale marker) verbatim in
    the head, gauge/trend value patches, and the z/breakdown bulk as
    self-contained qv sections — reassembled client-side onto a fresh
    copy of template ``tid``."""
    if _signature(frame) is None:
        raise WireError("frame shape is not template-encodable")
    head = {
        k: v
        for k, v in frame.items()
        if k not in _TEMPLATE_FIELDS and k != "breakdown"
    }
    patch = frame_patch(frame)
    for field in ("average", "device_rows", "trends"):
        if field in patch:
            head[field] = patch[field]
    head["tid"] = tid
    head_b: dict = {}
    out = bytearray()
    if "heatmaps" in patch:
        _encode_heatmaps(patch, None, head_b, out)
    if "breakdown" in patch:
        _encode_breakdown(patch, None, head_b, out)
    head["_b"] = head_b
    return _container(KIND_CFULL, head, bytes(out))


def decode_template(buf: bytes) -> dict:
    """Python-side template decode — a thin wrapper over the clientlogic
    decoder (the SAME code the page runs).  The returned dict carries
    its template id under ``_tid``."""
    kind, head, payload = split_container(buf)
    if kind != KIND_TEMPLATE:
        raise WireError(f"expected a template container, got kind {kind}")
    try:
        return clientlogic.decode_bin_template(head, payload)
    except WireError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, struct.error) as e:
        raise WireError(f"malformed template sections: {e!r}") from e


def decode_cfull(buf: bytes, template: dict) -> dict:
    """Reassemble one columnar full frame onto a deep copy of
    ``template`` (from decode_template).  WireError when the document
    references a template this consumer does not hold — the garbage-
    refusal path: numeric sections are never applied to the wrong
    structure."""
    import copy

    kind, head, payload = split_container(buf)
    if kind != KIND_CFULL:
        raise WireError(f"expected a cfull container, got kind {kind}")
    try:
        out = clientlogic.decode_bin_cfull(
            head, payload, copy.deepcopy(template)
        )
    except WireError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, struct.error) as e:
        raise WireError(f"malformed cfull sections: {e!r}") from e
    if out is None:
        raise WireError("cfull references a template this consumer lacks")
    return out


def fullc_envelope(tpl_buf: bytes, cfull_buf: bytes) -> bytes:
    """The self-contained columnar full frame (kind 6): template and
    cfull containers concatenated — what binary ``/api/frame`` serves
    (workers assemble it from the seal's two halves without re-encoding
    anything)."""
    return _container(
        KIND_FULLC, {"_b": {"t": len(tpl_buf)}}, tpl_buf + cfull_buf
    )


def encode_frame(frame: dict) -> bytes:
    """Binary FULL frame: the self-contained columnar envelope.  The
    figure structure, hover-text/customdata grids, chip table, and
    selection go columnar/interned (kind 4 half); z matrices, breakdown
    and every scalar ride the kind-5 half — at 4,096 chips the document
    is ~6x smaller than the JSON frame.  Raises WireError on shapes the
    patch protocol cannot reconstruct (callers fall back to JSON)."""
    tpl = encode_template(frame, "f")
    return fullc_envelope(tpl, encode_cfull(frame, "f"))


def decode_frame(buf: bytes) -> dict:
    """Inverse of encode_frame."""
    kind, head, payload = split_container(buf)
    if kind != KIND_FULLC:
        raise WireError(f"expected a full-frame envelope, got kind {kind}")
    try:
        tlen = int(head["_b"]["t"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed full-frame envelope head: {e!r}") from e
    if not 0 <= tlen <= len(payload):
        raise WireError("full-frame template length out of range")
    template = decode_template(bytes(payload[:tlen]))
    return decode_cfull(bytes(payload[tlen:]), template)


def event_body(evt: bytes) -> bytes:
    """The body slice of ONE complete framed stream event — how a
    worker lifts the cfull/template container back out of a seal's
    pre-framed event bytes to assemble the /api/frame envelope."""
    if len(evt) < 4:
        raise WireError("truncated stream event")
    idlen = evt[3]
    return evt[8 + idlen :]


def encode_summary(doc: dict) -> bytes:
    """Binary ``/api/summary`` (kind=3): the per-chip numeric matrix —
    the document's bulk — rides as raw little-endian float64 (NaN for
    null; full precision, the parent re-aggregates these), and the
    derivable ``keys`` list is dropped; identity/alerts/health stay in
    the JSON head.  ``doc["matrix"]`` may be the numpy block itself
    (the service's zero-copy path) or the JSON-shaped nested lists."""
    import numpy as np

    head = {k: v for k, v in doc.items() if k not in ("matrix", "keys")}
    payload = b""
    matrix = doc.get("matrix")
    if matrix is not None:
        if isinstance(matrix, np.ndarray):
            arr = np.ascontiguousarray(matrix, dtype=np.float64)
        else:
            arr = np.array(
                [
                    [np.nan if v is None else float(v) for v in row]
                    for row in matrix
                ],
                dtype=np.float64,
            )
        n = int(arr.shape[0])
        c = int(arr.shape[1]) if arr.ndim == 2 else 0
        head["_b"] = {"mx": {"n": n, "c": c}}
        payload = arr.tobytes()
    elif "keys" in doc:
        # table-less marker must survive the keys drop
        head["_b"] = {"mx": None}
    else:
        head["_b"] = {}
    return _container(KIND_SUMMARY, head, payload)


def decode_summary(buf: bytes) -> dict:
    """Inverse of encode_summary: returns the JSON-shaped doc with
    ``matrix`` as a float64 ndarray (consumers' fast path) and ``keys``
    re-derived from identity."""
    import numpy as np

    kind, head, payload = split_container(buf)
    if kind != KIND_SUMMARY:
        raise WireError(f"expected a summary container, got kind {kind}")
    head_b = head.pop("_b", {})
    mx = head_b.get("mx") if isinstance(head_b, dict) else None
    if mx is not None:
        try:
            n, c = int(mx["n"]), int(mx["c"])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"malformed summary matrix descriptor: {e!r}") from e
        if n < 0 or c < 0 or len(payload) != n * c * 8:
            raise WireError("summary matrix size disagrees with descriptor")
        # copy: frombuffer views are read-only, downstream batch math
        # assumes ordinary writable arrays
        head["matrix"] = (
            np.frombuffer(payload, dtype="<f8").reshape(n, c).copy()
        )
        ident = head.get("identity")
        if not isinstance(ident, dict):
            ident = {}
        try:
            head["keys"] = [
                f"{s}/{int(cid)}"
                for s, cid in zip(
                    ident.get("slice") or [], ident.get("chip_id") or []
                )
            ]
        except (TypeError, ValueError) as e:
            raise WireError(f"malformed summary identity: {e!r}") from e
    elif "mx" in (head_b or {}):
        head["keys"] = []  # table-less but valid (the no-table marker)
    return head


def _summary_matrix(doc: dict):
    """The doc's matrix as a float64 ndarray, or None (table-less /
    JSON-shaped docs are not delta material)."""
    import numpy as np

    m = doc.get("matrix")
    return m if isinstance(m, np.ndarray) and m.ndim == 2 else None


def encode_summary_delta(doc: dict, base_doc: dict, base_key: str) -> bytes:
    """Incremental ``/api/summary`` (kind 7): everything small rides the
    JSON head verbatim (minus identity/keys/cols — the base carries
    them); the matrix rides as a changed-cell bitmap plus one qv cell
    per changed position, anchored on the base matrix's cells.  Raises
    WireError whenever a delta cannot represent the transition (shape or
    identity changed, non-binary docs) — the caller serves the full doc
    unconditionally."""
    import numpy as np

    cur, base = _summary_matrix(doc), _summary_matrix(base_doc)
    if cur is None or base is None or cur.shape != base.shape:
        raise WireError("summary shapes differ — full doc required")
    # the WHOLE identity must match — not just the derived keys: a chip
    # keeping its slice/id but moving host (or an accel relabel) would
    # otherwise inherit the base's identity forever, since each
    # reconstructed doc becomes the next base and no full-doc resync
    # ever happens while shapes stay stable
    if doc.get("identity") != base_doc.get("identity") or list(
        doc.get("cols") or ()
    ) != list(base_doc.get("cols") or ()):
        raise WireError("summary identity changed — full doc required")
    head = {
        k: v
        for k, v in doc.items()
        if k not in ("matrix", "keys", "identity", "cols")
    }
    n, c = int(cur.shape[0]), int(cur.shape[1])
    head["_b"] = {"sd": {"n": n, "c": c, "base": base_key}}
    newf, oldf = cur.ravel(), base.ravel()
    changed = ~((newf == oldf) | (np.isnan(newf) & np.isnan(oldf)))
    bitmap = np.packbits(changed, bitorder="little").tobytes()
    out = bytearray(bitmap)
    idx = np.flatnonzero(changed)
    if len(idx):
        _qv_stream(out, newf[idx].tolist(), oldf[idx].tolist())
    return _container(KIND_SUMMARY_DELTA, head, bytes(out))


def _qv_decode_cells(payload: bytes, pos: int, bases, out) -> int:
    """Decode ``len(bases)`` qv cells off ``payload`` at ``pos`` into
    ``out`` (bases are the base100 anchors, matching the encoder's
    _cell_base derivation).  A tight scalar loop — the parent pays it
    only on changed-data polls, and changed cells are the minority in
    steady state; returns the final position."""
    nan, inf = float("nan"), float("inf")
    unpack_from = struct.unpack_from
    for j in range(len(bases)):
        n = payload[pos]
        pos += 1
        if n >= 0x80:
            n &= 0x7F
            shift = 7
            while True:
                b = payload[pos]
                pos += 1
                n |= (b & 0x7F) << shift
                if b < 0x80:
                    break
                shift += 7
        if n >= 5:
            d = n - 5
            d = -((d + 1) >> 1) if d & 1 else d >> 1
            out[j] = (bases[j] + d) / 100.0
        elif n == 4:
            out[j] = nan
        elif n == 1:
            out[j] = unpack_from("<d", payload, pos)[0]
            pos += 8
        elif n == 2:
            out[j] = inf
        elif n == 3:
            out[j] = -inf
        else:
            out[j] = nan  # code 0 (null) has no matrix spelling — NaN
    return pos


def decode_summary_delta(buf: bytes, base_doc: dict, base_key: str) -> dict:
    """Inverse of encode_summary_delta: reassembles the FULL summary doc
    onto ``base_doc`` (the parent's cached decode of the advertised
    base).  WireError when the document anchors on a different base than
    the caller holds — numeric deltas are never applied to the wrong
    matrix."""
    import numpy as np

    kind, head, payload = split_container(buf)
    if kind != KIND_SUMMARY_DELTA:
        raise WireError(f"expected a summary delta, got kind {kind}")
    head_b = head.pop("_b", None) or {}
    sd = head_b.get("sd") if isinstance(head_b, dict) else None
    if not isinstance(sd, dict):
        sd = {}
    if sd.get("base") != base_key:
        raise WireError(
            f"summary delta anchors on base {sd.get('base')!r}, "
            f"caller holds {base_key!r}"
        )
    base = _summary_matrix(base_doc)
    try:
        n, c = int(sd.get("n", -1)), int(sd.get("c", -1))
    except (TypeError, ValueError) as e:
        raise WireError(f"malformed summary-delta descriptor: {e!r}") from e
    if base is None or base.shape != (n, c):
        raise WireError("summary delta shape disagrees with held base")
    nbytes = (n * c + 7) // 8
    if len(payload) < nbytes:
        raise WireError("truncated summary-delta bitmap")
    changed = np.unpackbits(
        np.frombuffer(payload[:nbytes], dtype=np.uint8), bitorder="little"
    )[: n * c].astype(bool)
    matrix = base.copy().ravel()
    idx = np.flatnonzero(changed)
    if len(idx):
        oldf = matrix[idx]
        # the encoder's anchors via qd_base: exact-centi doubles anchor
        # at v*100, everything else (NaN, ±inf, sub-centi) at 0
        b100 = np.round(oldf * 100.0)
        ok = np.isfinite(oldf) & (b100 / 100.0 == oldf)
        ok &= np.abs(b100) < float(1 << 52)
        bases = np.where(ok, b100, 0.0)
        cells = np.empty(len(idx), dtype=np.float64)
        try:
            end = _qv_decode_cells(payload, nbytes, bases, cells)
        except (IndexError, struct.error) as e:
            # an internally-truncated payload (bitmap claims more cells
            # than the qv stream carries) is UNTRUSTED wire input: it
            # must refuse as a WireError → SourceError per child, never
            # escape as a parent-side bug that errors the whole frame
            raise WireError(f"truncated summary-delta cells: {e}") from e
        if end != len(payload):
            raise WireError("summary-delta payload length disagrees")
        matrix[idx] = cells
    elif len(payload) != nbytes:
        raise WireError("summary-delta payload length disagrees")
    doc = dict(head)
    doc["matrix"] = matrix.reshape(n, c)
    for k in ("identity", "cols", "keys"):
        if k in base_doc:
            doc[k] = base_doc[k]
    return doc


def binary_delta_roundtrip_equal(prev: dict, cur: dict) -> bool:
    """Test helper: does decode(encode(prev, frame_delta(prev, cur)))
    reproduce frame_delta(prev, cur) exactly?"""
    delta = frame_delta(prev, cur)
    if delta is None:
        return encode_delta(prev, delta) is None
    buf = encode_delta(prev, delta)
    return decode_delta(buf, prev) == delta
