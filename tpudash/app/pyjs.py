"""Python→JavaScript transpiler for the page's client logic.

Why this exists: the steady-state SSE transport depends on the browser's
``apply_delta`` mirroring ``tpudash/app/delta.py`` exactly — a
hand-maintained JS copy silently corrupts every tick the moment either
side drifts (VERDICT r3 weak #1), and this image ships NO JavaScript
engine (no node, no quickjs), so the JS can't be executed in tests.

The fix is to make drift *impossible* instead of detected: the client
logic is written ONCE, in Python (``tpudash/app/clientlogic.py``), where
the fuzz suite executes it directly against the reference merge; the
shipped JS is *generated* from that same Python source by this
transpiler at import time.  A parity test asserts the served page embeds
exactly the regenerated output, so hand-editing the JS or the Python
alone fails the suite.

The supported subset is deliberately tiny and VALUE-SEMANTICS-SAFE —
every construct below behaves identically on Python dict/list/scalar
data and its JSON counterpart in JS.  Anything outside the subset raises
``TranspileError`` at import (== CI) time.  Known semantic traps are
REJECTED, not translated:

- bare truthiness tests (``if x:``) — ``[]``/``{}``/``""``/``0`` differ
  between the languages; write explicit comparisons
- equality uses ``===``; ``in`` maps to JS ``in`` and is restricted to
  dict-like operands by convention (arrays would test indices)
- ``for x in expr`` → ``for (const x of expr)`` (arrays only);
  ``for i in range(len(x))`` → a classic counted loop; ``while``/
  ``break`` transpile directly
- ``%`` and ``//`` are allowed for the binary-wire decoder but agree
  between the languages only on NON-NEGATIVE operands (``//`` emits
  ``Math.floor(a / b)``) — the decoder's only use
- string repetition, slicing, comprehensions, try/except: unsupported,
  use explicit loops
"""

from __future__ import annotations

import ast
import inspect
import textwrap


class TranspileError(ValueError):
    pass


_CMP = {
    ast.Eq: "===",
    ast.NotEq: "!==",
    ast.Lt: "<",
    ast.LtE: "<=",
    ast.Gt: ">",
    ast.GtE: ">=",
}
_BINOP = {ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Div: "/"}
#: value-semantics caveats, enforced by convention in clientlogic (the
#: binary-wire decoder is the only user): `%` matches JS only for
#: NON-NEGATIVE operands (Python -1 % 3 == 2, JS -1 % 3 == -1), and
#: `//` transpiles to Math.floor(a / b), which matches Python float
#: floor-division — both are used exclusively on non-negative integers
#: and floats inside the decoder.


class _Fn:
    """Transpiles one function body.

    Locals are hoisted to ONE ``let`` declaration at the top of the
    function: Python locals are function-scoped, JS ``let`` is
    block-scoped — emitting ``let`` at first assignment inside an ``if``
    would silently leak later same-name assignments in sibling blocks to
    the global scope (or throw in strict mode)."""

    def __init__(self, params: "list[str]"):
        self.params = set(params)

    # -- expressions ---------------------------------------------------------
    def expr(self, node: ast.expr) -> str:
        if isinstance(node, ast.Constant):
            v = node.value
            if v is None:
                return "null"
            if v is True:
                return "true"
            if v is False:
                return "false"
            if isinstance(v, str):
                import json

                return json.dumps(v)
            if isinstance(v, (int, float)):
                return repr(v)
            raise TranspileError(f"unsupported constant {v!r}")
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Subscript):
            # negative indexes silently diverge (Python last-element vs
            # JS undefined) — reject them like the other known traps
            idx = node.slice
            if isinstance(idx, ast.Constant) and isinstance(
                idx.value, (int, float)
            ) and idx.value < 0:
                raise TranspileError("negative subscript diverges in JS")
            if isinstance(idx, ast.UnaryOp) and isinstance(idx.op, ast.USub):
                raise TranspileError("negative subscript diverges in JS")
            if isinstance(idx, ast.Slice):
                raise TranspileError("slice subscript unsupported")
            return f"{self.expr(node.value)}[{self.expr(idx)}]"
        if isinstance(node, (ast.List, ast.Tuple)):
            return "[" + ", ".join(self.expr(e) for e in node.elts) + "]"
        if isinstance(node, ast.Dict):
            parts = []
            for k, v in zip(node.keys, node.values):
                if k is None:
                    raise TranspileError("dict ** spread unsupported")
                parts.append(f"{self.expr(k)}: {self.expr(v)}")
            return "{" + ", ".join(parts) + "}"
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                raise TranspileError("chained comparisons unsupported")
            op, right = node.ops[0], node.comparators[0]
            left = self.expr(node.left)
            # OWN-property membership, not JS `in`: a data-controlled key
            # named "toString"/"constructor"/"__proto__" would be found
            # on Object.prototype by `in`, silently diverging from
            # Python dict membership (and crashing whatever indexes with
            # the inherited value next)
            if isinstance(op, ast.In):
                return (
                    f"({self.expr(right)} != null && "
                    f"Object.prototype.hasOwnProperty.call("
                    f"{self.expr(right)}, {left}))"
                )
            if isinstance(op, ast.NotIn):
                return (
                    f"!({self.expr(right)} != null && "
                    f"Object.prototype.hasOwnProperty.call("
                    f"{self.expr(right)}, {left}))"
                )
            if isinstance(op, (ast.Is, ast.IsNot)):
                # only `is [not] None`, mapped to LOOSE null equality: JS
                # has both null and undefined where Python has None, and
                # a missing JSON field reads as undefined — `x == null`
                # covers both, which is exactly the Python meaning here
                if not (
                    isinstance(right, ast.Constant) and right.value is None
                ):
                    raise TranspileError("`is` only supported against None")
                jsop = "==" if isinstance(op, ast.Is) else "!="
                return f"{left} {jsop} null"
            if type(op) in _CMP:
                return f"{left} {_CMP[type(op)]} {self.expr(right)}"
            raise TranspileError(f"unsupported comparison {ast.dump(op)}")
        if isinstance(node, ast.BoolOp):
            op = "&&" if isinstance(node.op, ast.And) else "||"
            return "(" + f" {op} ".join(self._bool(v) for v in node.values) + ")"
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            # parens: JS `!a === 0` parses as `(!a) === 0`
            return f"!({self._bool(node.operand)})"
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return f"-{self.expr(node.operand)}"
        if isinstance(node, ast.BinOp) and type(node.op) in _BINOP:
            return (
                f"({self.expr(node.left)} {_BINOP[type(node.op)]} "
                f"{self.expr(node.right)})"
            )
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            # % agrees between the languages only for non-negative
            # operands — the binary-wire decoder's only use (see the
            # module-note by _BINOP)
            return f"({self.expr(node.left)} % {self.expr(node.right)})"
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.FloorDiv):
            # Python float floor-division === Math.floor(a / b) for the
            # finite operands the decoder feeds it
            return (
                f"Math.floor({self.expr(node.left)} / "
                f"{self.expr(node.right)})"
            )
        if isinstance(node, ast.Call):
            return self.call(node)
        raise TranspileError(f"unsupported expression {ast.dump(node)[:80]}")

    def _bool(self, node: ast.expr) -> str:
        """Boolean context: only explicit booleans allowed — a bare name
        would carry Python-vs-JS truthiness differences ([] is true in
        JS)."""
        if isinstance(
            node, (ast.Compare, ast.BoolOp)
        ) or (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not)):
            return self.expr(node)
        if isinstance(node, ast.Constant) and isinstance(node.value, bool):
            return self.expr(node)
        if isinstance(node, ast.Name):
            raise TranspileError(
                f"bare truthiness of {node.id!r} is not value-semantics-safe"
                " — write an explicit comparison"
            )
        raise TranspileError(
            f"unsupported boolean operand {ast.dump(node)[:80]}"
        )

    def call(self, node: ast.Call) -> str:
        if node.keywords:
            raise TranspileError("keyword arguments unsupported")
        if isinstance(node.func, ast.Name):
            if node.func.id == "len":
                (arg,) = node.args
                return f"{self.expr(arg)}.length"
            if node.func.id == "keys":
                # Object.keys follows JS OrdinaryOwnPropertyKeys order:
                # integer-like keys ascend numerically FIRST, then the
                # rest in insertion order — NOT plain document order.
                # The Python helper (clientlogic.keys) and the jsmini
                # interpreter both replicate that exact ordering, so a
                # host named "10" sorts the same in tests and browsers.
                (arg,) = node.args
                return f"Object.keys({self.expr(arg)})"
            if node.func.id == "numstr":
                # integer → decimal string: String(n) on an integral JS
                # number prints exactly what Python str(int(n)) prints
                # (clientlogic.numstr is the Python twin, not transpiled)
                (arg,) = node.args
                return f"String({self.expr(arg)})"
            # calls to sibling transpiled functions pass through
            return (
                f"{node.func.id}("
                + ", ".join(self.expr(a) for a in node.args)
                + ")"
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "append"
            and len(node.args) == 1
        ):
            # list.append → Array.push (same in-place semantics)
            return (
                f"{self.expr(node.func.value)}.push({self.expr(node.args[0])})"
            )
        raise TranspileError(f"unsupported call {ast.dump(node.func)[:80]}")

    # -- statements ----------------------------------------------------------
    def stmt(self, node: ast.stmt, indent: str) -> str:
        if isinstance(node, ast.Assign):
            if len(node.targets) != 1:
                raise TranspileError("multi-target assignment unsupported")
            target = node.targets[0]
            value = self.expr(node.value)
            if isinstance(target, ast.Name):
                return f"{indent}{target.id} = {value};"
            if isinstance(target, ast.Subscript):
                return f"{indent}{self.expr(target)} = {value};"
            raise TranspileError("unsupported assignment target")
        if isinstance(node, ast.Delete):
            (target,) = node.targets
            if not isinstance(target, ast.Subscript):
                raise TranspileError("only `del d[k]` is supported")
            return f"{indent}delete {self.expr(target)};"
        if isinstance(node, ast.Return):
            if node.value is None:
                return f"{indent}return;"
            return f"{indent}return {self.expr(node.value)};"
        if isinstance(node, ast.If):
            out = [f"{indent}if ({self._test(node.test)}) {{"]
            out += [self.stmt(s, indent + "  ") for s in node.body]
            if node.orelse:
                out.append(f"{indent}}} else {{")
                out += [self.stmt(s, indent + "  ") for s in node.orelse]
            out.append(f"{indent}}}")
            return "\n".join(out)
        if isinstance(node, ast.For):
            if node.orelse:
                raise TranspileError("for-else unsupported")
            head = self._for_head(node)
            out = [f"{indent}{head} {{"]
            out += [self.stmt(s, indent + "  ") for s in node.body]
            out.append(f"{indent}}}")
            return "\n".join(out)
        if isinstance(node, ast.While):
            if node.orelse:
                raise TranspileError("while-else unsupported")
            return "\n".join(
                [f"{indent}while ({self._test(node.test)}) {{"]
                + [self.stmt(s, indent + "  ") for s in node.body]
                + [f"{indent}}}"]
            )
        if isinstance(node, ast.Break):
            return f"{indent}break;"
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            return f"{indent}{self.call(node.value)};"
        if isinstance(node, ast.Pass):
            return f"{indent};"
        raise TranspileError(f"unsupported statement {ast.dump(node)[:80]}")

    def _test(self, node: ast.expr) -> str:
        return self._bool(node)

    def _for_head(self, node: ast.For) -> str:
        if not isinstance(node.target, ast.Name):
            raise TranspileError("loop target must be a plain name")
        var = node.target.id
        it = node.iter
        # for i in range(len(x)):  →  counted loop.  The bound is CAPTURED
        # once (range() snapshots it in Python); a naive `i < x.length`
        # would re-read every iteration and loop forever if the body
        # appends to x — found by the differential fuzz.
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
        ):
            if len(it.args) != 1:
                raise TranspileError("only range(len(x)) loops supported")
            bound = self.expr(it.args[0])
            # bound FIRST: Python evaluates range()'s argument before
            # binding the loop variable, so `for i in range(f(i))` must
            # read the OLD i — `i = 0` before the bound would diverge
            return (
                f"for ({var}__n = {bound}, {var} = 0; "
                f"{var} < {var}__n; {var}++)"
            )
        # for x in <array expr>:  →  for-of (loop var hoisted like any
        # other local: Python loop variables outlive the loop)
        return f"for ({var} of {self.expr(it)})"


def transpile_function(fn) -> str:
    """One Python function (restricted subset) → a JS function of the
    same name.  Raises TranspileError outside the subset."""
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    (node,) = tree.body
    if not isinstance(node, ast.FunctionDef):
        raise TranspileError("expected a single function definition")
    a = node.args
    if a.vararg or a.kwarg or a.kwonlyargs or a.defaults or a.posonlyargs:
        raise TranspileError("only plain positional parameters supported")
    params = [p.arg for p in a.args]
    t = _Fn(params)
    body = node.body
    # skip a leading docstring
    if (
        body
        and isinstance(body[0], ast.Expr)
        and isinstance(body[0].value, ast.Constant)
        and isinstance(body[0].value.value, str)
    ):
        body = body[1:]
    locals_ = _collect_locals(body, set(params))
    lines = [f"function {node.name}({', '.join(params)}) {{"]
    if locals_:
        lines.append("  let " + ", ".join(sorted(locals_)) + ";")
    lines += [t.stmt(s, "  ") for s in body]
    lines.append("}")
    return "\n".join(lines)


def _collect_locals(body, params: set) -> "set[str]":
    """Every name assigned or used as a loop target in the function body
    (minus parameters) — hoisted into one function-top ``let``."""
    names: set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
            self.generic_visit(node)

        def visit_For(self, node):
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
                it = node.iter
                if (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range"
                ):
                    # counted loops capture their bound in <var>__n
                    names.add(f"{node.target.id}__n")
            self.generic_visit(node)

    v = V()
    for s in body:
        v.visit(s)
    return names - params


def transpile_functions(fns) -> str:
    """Several functions → one JS block, preceded by a provenance note."""
    header = (
        "// GENERATED from tpudash/app/clientlogic.py by tpudash/app/pyjs.py"
        " — do not edit;\n// the Python source is the fuzz-tested single"
        " source of truth (tests/test_client_parity.py)."
    )
    return header + "\n" + "\n".join(transpile_function(f) for f in fns)
