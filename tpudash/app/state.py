"""Selection / style state with the reference's session semantics.

The reference keeps three session keys (SURVEY.md §3.4): ``selected_gpus``
(pruned against available devices app.py:281, defaulting to the first device
when empty app.py:284-285, re-sorted after changes app.py:313),
``use_gauge`` (app.py:254-260) and ``last_selection`` (app.py:274-275, 310).
SelectionState reproduces exactly those behaviors keyed by chip key strings,
sorting numerically by (slice, chip) — not lexically.
"""

from __future__ import annotations


def _sort_key(chip_key: str):
    slice_id, _, chip = chip_key.rpartition("/")
    try:
        return (slice_id, int(chip))
    except ValueError:
        return (slice_id, -1)


class SelectionState:
    def __init__(self) -> None:
        self.selected: list[str] = []
        self.last_selection: list[str] = []
        self.use_gauge: bool = True
        self._initialized = False

    def sync(self, available: list[str]) -> list[str]:
        """Reconcile selections with the currently available chips:
        prune stale keys (app.py:281), default to the first chip when the
        selection is empty (app.py:284-285), keep sorted (app.py:313)."""
        avail = sorted(available, key=_sort_key)
        self.selected = [k for k in self.selected if k in set(avail)]
        if not self.selected and avail and not self._initialized:
            self.selected = [avail[0]]
        self._initialized = True
        self.selected.sort(key=_sort_key)
        return self.selected

    def set_selected(self, keys: list[str], available: list[str]) -> list[str]:
        """Replace the selection (checkbox-grid change, app.py:292-313)."""
        self.last_selection = list(self.selected)
        avail = set(available)
        self.selected = sorted(
            {k for k in keys if k in avail}, key=_sort_key
        )
        return self.selected

    def toggle(self, chip_key: str, available: list[str]) -> list[str]:
        """Flip one checkbox (app.py:292-309)."""
        self.last_selection = list(self.selected)
        if chip_key in self.selected:
            self.selected.remove(chip_key)
        elif chip_key in set(available):
            self.selected.append(chip_key)
            self.selected.sort(key=_sort_key)
        return self.selected

    def select_all(self, available: list[str]) -> list[str]:
        self.last_selection = list(self.selected)
        self.selected = sorted(available, key=_sort_key)
        return self.selected

    def clear(self) -> list[str]:
        self.last_selection = list(self.selected)
        self.selected = []
        return self.selected
