"""Overload protection — admission control, load shedding, SSE accounting.

PR 1 hardened the *source* side (breakers, watchdog, concurrent multi
fetch); this is the *serving* side's equivalent: the dashboard must
degrade gracefully under a client swarm instead of falling over with the
fleet it monitors.  Three mechanisms, all owned by :class:`OverloadGuard`
and driven from the server's admission middleware:

- a **global concurrency gate** (``Config.max_concurrency``) bounding
  simultaneously-served requests, so a request flood queues in the
  kernel's accept backlog instead of starving the event loop that the
  refresh watchdog and webhook delivery share;
- **per-client token buckets** (``Config.rate_limit`` / ``rate_burst``)
  keyed by session cookie (peer address fallback), so one misbehaving
  dashboard tab cannot crowd out every other viewer;
- **bounded SSE fan-out**: a stream cap (``Config.max_streams``) and
  per-event write-deadline eviction accounting (the deadline itself is
  enforced in the server's stream loop — the guard only counts).

Shed requests get ``503`` + ``Retry-After``; ``GET /api/frame`` degrades
to the last published frame with a ``stale: true`` marker instead, and
``/healthz`` is never shed (liveness must not flap under load).

The guard also runs the **overload state machine** the rest of the stack
observes (``/healthz``, the synthesized ``overload`` alert, the
``/api/timings`` counters):

    normal ──(any shed in the window)──▶ shedding
    shedding ──(a gate/cap is full *right now*)──▶ saturated
    saturated/shedding ──(no shed for SHED_WINDOW_S)──▶ normal

Threading: every *mutation* happens on the aiohttp event loop (no locks
needed).  :meth:`snapshot` is read-only and safe from worker threads —
the service's alert synthesis calls it from ``refresh_data`` on the
executor.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque

#: a shed inside this window keeps the state machine out of "normal"
SHED_WINDOW_S = 10.0

#: bound on the per-client bucket map (LRU evicted) — a spoofed-cookie
#: swarm must not grow server memory without bound
MAX_CLIENT_BUCKETS = 4096

#: shed-reason keys (also the counter names, prefixed ``shed_``)
SHED_RATE = "rate_limited"
SHED_CONCURRENCY = "concurrency"
SHED_STREAMS = "streams"


def bound_stream_buffers(request, sndbuf: int) -> None:
    """Clamp one SSE connection's outbound buffering to ``sndbuf`` bytes
    (``Config.sse_sndbuf``): both the kernel socket send buffer and
    aiohttp's transport write buffer.  Unbounded auto-tuned buffers cost
    real memory per wedged consumer at thousands of streams AND absorb a
    stall silently — the write deadline can only evict a slow consumer
    whose writes actually block.  No-op when ``sndbuf`` is 0 or the
    transport is already gone."""
    if sndbuf <= 0:
        return
    import socket as socketmod

    transport = request.transport
    if transport is None:
        return
    sock = transport.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_SNDBUF, sndbuf)
        except OSError:
            return  # already disconnecting — nothing to bound
    transport.set_write_buffer_limits(high=sndbuf)


class TokenBucket:
    """Classic token bucket on a monotonic clock: ``rate`` tokens/s up to
    ``burst``; one token per admitted request."""

    __slots__ = ("tokens", "stamp")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.stamp = now

    def admit(self, rate: float, burst: float, now: float) -> bool:
        self.tokens = min(burst, self.tokens + (now - self.stamp) * rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class OverloadGuard:
    """Admission state for one :class:`DashboardServer` (see module doc)."""

    def __init__(self, cfg, clock=time.monotonic):
        self.max_concurrency = max(0, int(cfg.max_concurrency))
        self.rate = max(0.0, float(cfg.rate_limit))
        burst = float(cfg.rate_burst) if cfg.rate_burst else 2.0 * self.rate
        self.burst = max(1.0, burst) if self.rate else 0.0
        self.max_streams = max(0, int(cfg.max_streams))
        self.write_deadline = max(0.0, float(cfg.sse_write_deadline))
        retry = float(cfg.shed_retry_after)
        if retry <= 0:
            retry = max(1.0, float(cfg.refresh_interval))
        self.retry_after = retry
        self._clock = clock
        self.inflight = 0
        self.streams = 0
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self.counters = {
            "admitted": 0,
            f"shed_{SHED_RATE}": 0,
            f"shed_{SHED_CONCURRENCY}": 0,
            f"shed_{SHED_STREAMS}": 0,
            "evicted_slow_consumers": 0,
            "stale_frames_served": 0,
        }
        #: monotonic stamps of recent sheds (state-machine input); bounded
        #: — the window sum saturates long before the bound matters
        self._recent_sheds: deque = deque(maxlen=1024)
        self._state = "normal"
        self._state_since = clock()

    # -- admission -----------------------------------------------------------
    @staticmethod
    def client_key(request) -> str:
        """Rate-limit key: the session cookie when present (one browser =
        one budget, however many tabs), else the peer address (curl, API
        consumers, proxies without cookies)."""
        from tpudash.app.server import SESSION_COOKIE

        sid = request.cookies.get(SESSION_COOKIE)
        if sid:
            return f"sid:{sid}"
        peer = request.remote or ""
        return f"peer:{peer}"

    def admit(self, key: str, gate: bool = True) -> "str | None":
        """Try to admit one request.  Returns None on admission (the
        caller MUST pair it with :meth:`release` when ``gate`` was True)
        or the shed reason.  ``gate=False`` skips the concurrency gate
        (SSE streams: held open for minutes, governed by the stream cap
        instead — they must not consume the request gate forever)."""
        now = self._clock()
        # gate BEFORE the rate debit: a gate-shed request must not also
        # burn the client's token, or a polite client retrying per
        # Retry-After through a gate-full episode drains its bucket and
        # keeps being shed (as rate_limited) after capacity frees
        if gate and self.max_concurrency and self.inflight >= self.max_concurrency:
            self._shed(SHED_CONCURRENCY, now)
            return SHED_CONCURRENCY
        if self.rate > 0:
            bucket = self._buckets.get(key)
            if bucket is None:
                while len(self._buckets) >= MAX_CLIENT_BUCKETS:
                    self._buckets.popitem(last=False)
                bucket = self._buckets[key] = TokenBucket(self.burst, now)
            else:
                self._buckets.move_to_end(key)
            if not bucket.admit(self.rate, self.burst, now):
                self._shed(SHED_RATE, now)
                return SHED_RATE
        if gate:
            self.inflight += 1
            # gate=False (SSE) requests are counted admitted by
            # acquire_stream() instead — the stream cap can still shed
            # them after this point, and one request must never show up
            # as both admitted and shed in the runbook's counters
            self.counters["admitted"] += 1
        self._transition(now)
        return None

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)

    # -- SSE stream accounting -----------------------------------------------
    def acquire_stream(self) -> bool:
        now = self._clock()
        if self.max_streams and self.streams >= self.max_streams:
            self._shed(SHED_STREAMS, now)
            return False
        self.streams += 1
        self.counters["admitted"] += 1
        self._transition(now)
        return True

    def release_stream(self) -> None:
        self.streams = max(0, self.streams - 1)

    def note_eviction(self) -> None:
        self.counters["evicted_slow_consumers"] += 1

    def note_stale_frame(self) -> None:
        self.counters["stale_frames_served"] += 1

    def retry_after_header(self) -> str:
        """Integer seconds for the ``Retry-After`` header (RFC 9110
        allows only whole seconds; round up so we never invite an
        earlier retry than configured)."""
        return str(max(1, int(-(-self.retry_after // 1))))

    # -- state machine -------------------------------------------------------
    def _shed(self, reason: str, now: float) -> None:
        self.counters[f"shed_{reason}"] += 1
        self._recent_sheds.append(now)
        self._transition(now)

    def _recent(self, now: float) -> int:
        # tuple(): snapshot() may race an append from the event loop —
        # iterating a copy keeps the worker-thread read safe
        return sum(1 for t in tuple(self._recent_sheds) if now - t < SHED_WINDOW_S)

    def _compute_state(self, now: float) -> str:
        if self._recent(now) == 0:
            return "normal"
        gate_full = bool(
            self.max_concurrency and self.inflight >= self.max_concurrency
        )
        streams_full = bool(
            self.max_streams and self.streams >= self.max_streams
        )
        return "saturated" if gate_full or streams_full else "shedding"

    def _transition(self, now: float) -> None:
        """Advance the state machine (event-loop callers only)."""
        state = self._compute_state(now)
        if state != self._state:
            self._state = state
            self._state_since = now

    def state(self) -> str:
        now = self._clock()
        self._transition(now)
        return self._state

    def snapshot(self) -> dict:
        """Read-only summary (safe from any thread): state, since, live
        gauges, limits, and the monotonically-growing counters that
        ``/api/timings`` and the runbook read."""
        now = self._clock()
        state = self._compute_state(now)
        # a decayed/advanced state the loop hasn't stamped yet reports
        # "since now" rather than a stale transition time
        since = self._state_since if state == self._state else now
        total_shed = sum(
            v for k, v in self.counters.items() if k.startswith("shed_")
        )
        return {
            "state": state,
            "since_s": round(now - since, 3),
            "recent_sheds": self._recent(now),
            "inflight": self.inflight,
            "streams": self.streams,
            "total_shed": total_shed,
            "limits": {
                "max_concurrency": self.max_concurrency,
                "rate_limit": self.rate,
                "rate_burst": self.burst,
                "max_streams": self.max_streams,
                "sse_write_deadline_s": self.write_deadline,
            },
            "counters": dict(self.counters),
        }
