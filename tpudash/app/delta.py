"""Frame-diff transport: steady-state SSE ticks send values, not layout.

A full 256-chip select-all frame serializes to ~125 KB (BENCH_r03
``sse_full_frame_bytes``) because every tick re-ships figure *structure*:
axis bands, colorscales, hover prefixes, customdata key grids, titles.
Between two frames of the same shape, only the *values* move: gauge
readings (and their band color), heatmap z-matrices, sparkline points,
stats, breakdowns, alerts, timings.

``frame_delta(prev, cur)`` returns that value-only payload — or None
whenever the structural signature changed (selection, style, panel set,
chip population, axis maxima, figure types), in which case the caller
sends a full frame.  ``apply_delta(prev, delta)`` is the reference merge:
``apply_delta(prev, frame_delta(prev, cur)) == cur`` exactly (pinned by
tests/test_delta.py); the page's ``applyDelta`` in app/html.py mirrors it
field for field — change both together.
"""

from __future__ import annotations

import copy

#: top-level frame fields copied verbatim into every delta (cheap, and
#: they change every tick or matter for correctness when they do)
SCALAR_FIELDS = (
    "last_updated",
    "timings",
    "source_health",
    "alerts",
    "stragglers",
    "warnings",
    "stats",
    "breakdown",
    "unavailable_panels",
)


def _gauge_like(figure: dict) -> tuple:
    """(type, axis_max) for a gauge/bar panel figure.  Any other trace
    type raises: _signature's catch turns that into a full-frame fallback
    instead of letting _fig_value crash the stream on a figure kind the
    patch protocol doesn't know."""
    trace = figure["data"][0]
    if trace["type"] == "indicator":
        return ("indicator", trace["gauge"]["axis"]["range"][1])
    if trace["type"] == "bar":
        return ("bar", figure["layout"]["xaxis"]["range"][1])
    raise TypeError(f"unpatchable figure type {trace['type']!r}")


def _signature(frame: dict) -> "tuple | None":
    """Structural fingerprint: two frames with equal signatures can be
    patched into each other with values alone."""
    if frame.get("error") is not None:
        return None  # error frames have no figures — always send full
    avg = frame.get("average")
    try:
        return (
            frame.get("use_gauge"),
            frame.get("refresh_interval"),
            tuple(frame.get("selected", ())),
            tuple(
                (c["key"], c.get("model"), c.get("host"), c.get("slice"))
                for c in frame.get("chips", ())
            ),
            tuple(p["column"] for p in frame.get("panel_specs", ())),
            tuple(
                (f["panel"], _gauge_like(f["figure"]))
                for f in (avg["figures"] if avg else ())
            ),
            tuple(
                (
                    r["key"],
                    tuple(
                        (f["panel"], _gauge_like(f["figure"]))
                        for f in r["figures"]
                    ),
                )
                for r in frame.get("device_rows", ())
            ),
            tuple(
                (
                    h["panel"],
                    h["slice"],
                    len(h["figure"]["data"][0]["z"]),
                    len(h["figure"]["data"][0]["z"][0]),
                    h["figure"]["data"][0].get("zmax"),
                )
                for h in frame.get("heatmaps", ())
            ),
            tuple(
                (
                    t["panel"],
                    t["figure"]["layout"]["yaxis"]["range"][1],
                )
                for t in frame.get("trends", ())
            ),
        )
    except (KeyError, IndexError, TypeError):
        return None  # unexpected shape → be safe, send full


def _fig_value(figure: dict) -> dict:
    trace = figure["data"][0]
    if trace["type"] == "indicator":
        return {"value": trace["value"], "color": trace["gauge"]["bar"]["color"]}
    return {"value": trace["x"][0], "color": trace["marker"]["color"]}


def frame_patch(cur: dict) -> dict:
    """The value-only payload of ``cur``, extracted unconditionally:
    every scalar field plus the gauge/heatmap/trend value patches.
    ONE extraction shared by the two transports that claim the same
    patch contract — frame_delta (anchored on prev) and the columnar
    cfull (tpudash/app/wire.py, anchored on a figure template) — so
    they can never silently disagree about frame content."""
    patch: dict = {}
    for field in SCALAR_FIELDS:
        if field in cur:
            patch[field] = cur[field]
    avg = cur.get("average")
    if avg:
        patch["average"] = [_fig_value(f["figure"]) for f in avg["figures"]]
    if cur.get("device_rows"):
        patch["device_rows"] = [
            [_fig_value(f["figure"]) for f in r["figures"]]
            for r in cur["device_rows"]
        ]
    if cur.get("heatmaps"):
        patch["heatmaps"] = [
            h["figure"]["data"][0]["z"] for h in cur["heatmaps"]
        ]
    if cur.get("trends"):
        patch["trends"] = [
            {
                "x": t["figure"]["data"][0]["x"],
                "y": t["figure"]["data"][0]["y"],
                "color": t["figure"]["data"][0]["line"]["color"],
            }
            for t in cur["trends"]
        ]
    return patch


def frame_delta(prev: "dict | None", cur: dict) -> "dict | None":
    """Value-only patch taking ``prev`` to ``cur``, or None when the
    structure changed and only a full frame is faithful."""
    if prev is None:
        return None
    sig = _signature(cur)
    if sig is None or sig != _signature(prev):
        return None
    return {"kind": "delta", **frame_patch(cur)}


def apply_delta(prev: dict, delta: dict) -> dict:
    """Reference merge (the page's JS applyDelta mirrors this).  Returns a
    NEW frame dict; ``prev`` is not mutated."""
    frame = copy.deepcopy(prev)
    for field in SCALAR_FIELDS:
        if field in delta:
            frame[field] = delta[field]
        else:
            frame.pop(field, None)

    def patch_fig(figure: dict, patch: dict) -> None:
        trace = figure["data"][0]
        if trace["type"] == "indicator":
            trace["value"] = patch["value"]
            trace["gauge"]["bar"]["color"] = patch["color"]
        else:
            trace["x"] = [patch["value"]]
            trace["marker"]["color"] = patch["color"]

    if "average" in delta:
        for f, patch in zip(frame["average"]["figures"], delta["average"]):
            patch_fig(f["figure"], patch)
    if "device_rows" in delta:
        for row, patches in zip(frame["device_rows"], delta["device_rows"]):
            for f, patch in zip(row["figures"], patches):
                patch_fig(f["figure"], patch)
    if "heatmaps" in delta:
        for h, z in zip(frame["heatmaps"], delta["heatmaps"]):
            h["figure"]["data"][0]["z"] = z
    if "trends" in delta:
        for t, patch in zip(frame["trends"], delta["trends"]):
            trace = t["figure"]["data"][0]
            trace["x"] = patch["x"]
            trace["y"] = patch["y"]
            trace["line"]["color"] = patch["color"]
    return frame
