"""DashboardService — one frame = scrape → normalize → figures.

The testable core of L4 (the reference mixes this into its render loop,
app.py:320-486).  ``render_frame()`` returns a JSON-able dict with:

- ``chips``: the selection-grid model (key, chip_id, slice, host, model) —
  the reference's checkbox grid source (app.py:266-313);
- ``average``: panel row averaged over selected chips, zero-exclusion
  power policy applied (app.py:341-345), plus chip count;
- ``device_rows``: per-chip panel rows with model-aware power maxima and
  headers "TPU {id} ({model})" (app.py:411-476) — only emitted while the
  selection is small (config.per_chip_panel_limit);
- ``heatmaps``: one topology heatmap per panel metric across ALL selected
  chips — the O(1)-figures path that replaces per-chip rows at 256-chip
  scale (SURVEY.md §3.2 scaling wall);
- ``stats``: mean/max/min table rounded to 2 dp (app.py:478-481);
- ``error``: the error-banner string when the source failed this cycle —
  the app keeps polling (app.py:225-227, 333);
- ``timings``: scrape/normalize/render stage p50s (SURVEY.md §5 tracing).
"""

from __future__ import annotations

import contextlib
import copy
import datetime as _dt
import functools
import logging
import os
import threading
import time
from collections import deque

import numpy as np
import pandas as pd

log = logging.getLogger(__name__)

from tpudash import schema
from tpudash.config import Config
from tpudash.normalize import (
    block_average,
    column_average,
    compute_stats,
    dense_block,
    filter_selected,
    to_wide,
    chip_links,
    torus_neighbor_keys,
)
from tpudash.app.state import SelectionState
from tpudash.registry import resolve_generation
from tpudash.sources.base import MetricsSource
from tpudash.topology import heatmap_grid_arrays, topology_for
from tpudash.utils.timing import StageTimer
from tpudash.viz.dispatch import accel_types_for, create_visualization, panel_max
from tpudash.viz.figures import (
    create_sparkline,
    create_topology_heatmap,
    key_grid,
)


#: Known real-world dialect gaps, shown when a reference-parity panel has
#: no series in the current scrape: neither the GKE tpu-device-plugin nor
#: the libtpu runtime-metrics surface carries power or temperature
#: (tpudash.compat SERIES_ALIASES cover duty-cycle/HBM/MXU/mem-BW only) —
#: only the in-repo exporter/probe sources provide them.
PANEL_GAP_REASONS = {
    schema.POWER: (
        "no power series in this scrape — the GKE tpu-device-plugin and "
        "libtpu runtime dialects do not export power; use the tpudash "
        "exporter/probe source for it"
    ),
    schema.TEMPERATURE: (
        "no temperature series in this scrape — the GKE tpu-device-plugin "
        "and libtpu runtime dialects do not export temperature; use the "
        "tpudash exporter/probe source for it"
    ),
    schema.ICI_LINK_MIN_GBPS: (
        "no per-link ICI series (tpu_ici_link_*) in this scrape — the "
        "probe source emits the local x pair; the synthetic source emits "
        "all directions by default (TPUDASH_SYNTHETIC_LINKS=0 disables)"
    ),
}
_GENERIC_GAP = "no source series in the current scrape"


def _merge_alerts(primary: "list[dict]", secondary: "list[dict]") -> "list[dict]":
    """Union keyed (rule, chip), ``primary`` winning duplicates: the
    parent engine's own evaluation of a federated table beats a child's
    passthrough copy of the same (rule, chip) — both describe the same
    breach, and the engine's entry carries the parent's hysteresis —
    and on error cycles a freshly-rolled-up child alert beats the
    previous frame's kept copy."""
    seen = {(a.get("rule"), a.get("chip")) for a in primary}
    return primary + [
        a for a in secondary if (a.get("rule"), a.get("chip")) not in seen
    ]


def _downsample(pts: list, max_points: int) -> "tuple[list, dict]":
    """(strided points anchored at the newest, {ts: "HH:MM:SS"} labels) —
    shared by the fleet sparklines and the per-chip drill-down trends."""
    stride = max(1, -(-len(pts) // max_points))
    pts = pts[::-1][::stride][::-1]
    fmt = {
        ts: _dt.datetime.fromtimestamp(ts).strftime("%H:%M:%S")
        for ts, _ in pts
    }
    return pts, fmt


@functools.lru_cache(maxsize=256)
def _model_name(accel: str) -> str:
    gen = resolve_generation(accel)
    # Unknown models render as "unknown", not "None" (reference quirk at
    # app.py:415 not replicated).
    return gen.name if gen else (accel or "unknown")


class _AttrRestore:
    """Adapter putting a plain dict attribute (MultiSource.last_errors)
    on the same (obj, snapshot) rollback list SourceHealth/CircuitBreaker
    use in synthetic_load.  Restores by REBINDING the attribute — fetch()
    assigns a fresh dict each cycle, so mutating the snapshotted object
    would silently miss."""

    def __init__(self, obj, attr: str):
        self._obj = obj
        self._attr = attr

    def restore(self, snap: dict) -> None:
        setattr(self._obj, self._attr, dict(snap))


class DashboardService:
    def __init__(self, cfg: Config, source: MetricsSource):
        self.cfg = cfg
        self.source = source
        self.state = SelectionState()
        self.timer = StageTimer()
        #: True between refresh_data() and the first compose_frame() that
        #: records the render stage and closes the timer frame
        self._frame_open = False
        #: data-pull wall time shown on every frame composed from it
        self.last_updated: str = _dt.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        )
        #: the same stamp as an epoch float — the machine-readable twin
        #: /api/summary publishes so a federation parent can measure
        #: data age without parsing the display string.
        # tpulint: allow[wall-clock] scrape stamps are epoch timestamps
        self.last_updated_ts: float = time.time()
        #: per-refresh identity extraction shared across session composes
        self._chips_base: list = []
        self._ident_chips = None
        self._ident_slices = None
        self._ident_keys = None
        self._ident_accels: list = []
        #: columnar-arena bookkeeping: the pandas Index object of the
        #: frame the identity caches were extracted from.  normalize's
        #: wide arena reuses the Index object while the population holds
        #: still, so `df.index is self._ident_index` proves the whole
        #: identity block (keys, chips grid, group codes) is current —
        #: steady-state publishes skip every per-chip Python loop.
        self._ident_index = None
        self._keys_list: list = []
        #: population-keyed compose caches (chips grid with selection
        #: flags, per-dimension group codes, per-slice heatmap geometry)
        self._chips_sel_cache: "tuple | None" = None
        self._group_cache: "dict | None" = None
        self._heatmap_geo: "dict | None" = None
        self._trend_cache: "tuple | None" = None
        self._strftime_cache: dict = {}
        self.last_error: str | None = None
        #: set by the server's refresh watchdog while a fetch is stalled
        #: (frames keep serving the last data with this warning attached)
        self.refresh_stalled: "str | None" = None
        #: serializes data publication against frame composition: a fetch
        #: parked by the watchdog completes on its executor thread while
        #: composes keep running — without this, a recovering refresh
        #: could swap last_df/identity caches mid-compose (torn frames)
        self._publish_lock = threading.RLock()
        #: wide per-chip table from the last successful frame (CSV export)
        self.last_df: "pd.DataFrame | None" = None
        #: chip keys seen in the last successful frame — the "currently
        #: available devices" selection ops validate against (app.py:281).
        self.available: list[str] = []
        #: the composite state checkpoint, parsed ONCE: UI state here,
        #: silences below, per-browser sessions by DashboardServer
        from tpudash.app.state import read_state_doc

        self._restored_state_doc: dict = (
            read_state_doc(cfg.state_path) or {} if cfg.state_path else {}
        )
        if self._restored_state_doc and self.state.load_dict(
            self._restored_state_doc
        ):
            log.info("restored UI state from %s", cfg.state_path)
        #: rolling (wall_ts, {column: fleet-average}) per successful
        #: frame — trend history the reference never kept.  At the default
        #: 5 s cadence, the default 720 points ≈ one hour.
        self.history: deque = deque(maxlen=max(2, cfg.history_points))
        #: per-CHIP rolling history for the drill-down view: (wall_ts,
        #: float32 matrix) aligned to _chip_hist_keys rows and
        #: _chip_hist_cols columns.  720 × 256 chips × ~10 metrics ≈ 7 MB
        #: (cfg.history_points scales it for larger fleets).  The ring
        #: resets when the chip population or metric set changes (slice
        #: resize, new exporter) — alignment beats splicing.
        self.chip_history: deque = deque(maxlen=max(2, cfg.history_points))
        self._chip_hist_keys: list = []
        self._chip_hist_cols: list = []
        self._chip_hist_rowmap: dict = {}
        #: full-table dense block from the last refresh — shared by the
        #: history appends and select-all composes
        self._df_block = (None, [])
        #: the long-horizon compressed trend store (tpudash.tsdb): every
        #: ring append mirrors into it, sparklines/drill-downs serve
        #: from it once it holds more than the rings, and /api/range is
        #: its query surface.  Always on (in-memory when TPUDASH_TSDB_PATH
        #: is unset); never a startup crash — the dashboard must run
        #: even when the store's volume is gone.
        from tpudash.tsdb import TSDB

        #: cold archive tier (tpudash.tsdb.cold): sealed segments fold
        #: into digest-verified object-store bundles off the seal thread;
        #: /api/range, sketch quantiles, and anomaly replay span hot→cold
        #: transparently, a dark store degrades answers to the hot
        #: horizon with ``partial: true`` + a cold_unreachable alert, and
        #: segment reclaim refuses to retire anything unverified.  Built
        #: BEFORE the store so the load-time retention pass already sees
        #: the reclaim gate: segments that expired while the process was
        #: down must not be retired before the catalog can vouch for them.
        self.cold = None
        self.compactor = None
        if cfg.cold_store:
            try:
                from tpudash.tsdb.cold import ColdTier
                from tpudash.tsdb.objstore import open_store

                source_dir = cfg.tsdb_follow or cfg.tsdb_path
                cache_dir = cfg.cold_cache_dir or (
                    os.path.join(source_dir, "cold-cache")
                    if source_dir
                    else ""
                )
                if not cache_dir:
                    raise ValueError(
                        "cold tier needs TPUDASH_COLD_CACHE_DIR when "
                        "the tsdb is memory-only"
                    )
                self.cold = ColdTier(
                    open_store(cfg.cold_store),
                    cache_dir=cache_dir,
                    cache_max_bytes=cfg.cold_cache_mb << 20,
                )
            except Exception as e:  # noqa: BLE001 — archive tier is best-effort
                log.warning("cold tier unavailable: %s", e)
                self.cold = None
        try:
            if cfg.tsdb_follow:
                # follower (hot-standby) mode: tail another instance's
                # segment directory read-only — /api/range, sparklines,
                # and drill-downs serve from the standby with a measured
                # replication lag; local ingest is inert by contract
                from tpudash.tsdb.follower import FollowerTSDB

                if cfg.tsdb_path:
                    log.warning(
                        "TPUDASH_TSDB_FOLLOW set: ignoring TPUDASH_TSDB_PATH"
                        " — a follower never writes segments of its own"
                    )
                follower = FollowerTSDB.from_config(cfg)
                if self.cold is not None:
                    # a follower never reclaims (read-only by contract),
                    # so post-construction attach carries no race
                    follower.attach_cold(self.cold)
                follower.start()
                self.tsdb: "TSDB | None" = follower
            else:
                self.tsdb = TSDB.from_config(cfg, cold=self.cold)
        except Exception as e:  # noqa: BLE001 — history tier is best-effort
            log.warning("tsdb unavailable: %s", e)
            self.tsdb = None
        if self.cold is not None and self.tsdb is None:
            self.cold.close()
            self.cold = None
        if self.cold is not None:
            try:
                from tpudash.tsdb.compact import Compactor

                source_dir = cfg.tsdb_follow or cfg.tsdb_path
                # the compactor runs on leaders AND followers (reading
                # sealed segment files is role-agnostic; deterministic
                # bundle names + digest verify make concurrent sweeps
                # idempotent) — TPUDASH_COLD_COMPACT=false pins an
                # instance read-only for running compaction off the
                # serving leader
                if cfg.cold_compact and cfg.cold_interval > 0 and source_dir:
                    self.compactor = Compactor(
                        source_dir=source_dir,
                        cold=self.cold,
                        interval_s=cfg.cold_interval,
                        min_age_s=cfg.cold_min_age,
                        max_bundle_bytes=cfg.cold_bundle_mb << 20,
                        upload_deadline_s=cfg.cold_upload_deadline,
                    )
                    self.compactor.start()
            except Exception as e:  # noqa: BLE001 — archive tier is best-effort
                log.warning("cold compactor unavailable: %s", e)
                self.compactor = None
        #: recording rules (tpudash.analytics.rules): derived series —
        #: fleet MFU, per-slice/per-host aggregates, the anomaly score —
        #: evaluated once per sealed chunk ON THE SEAL THREAD and
        #: persisted as first-class ``__rule__/<name>`` series, so every
        #: viewer (and the anomaly layer) queries precomputed series
        #: instead of re-deriving them per tick.  Leaders only — a
        #: follower receives rule blocks through replication.
        self.rule_engine = None
        if self.tsdb is not None and not getattr(self.tsdb, "read_only", False):
            from tpudash.analytics.rules import RuleEngine

            try:
                self.rule_engine = RuleEngine.from_config(cfg)
            except ValueError as e:
                log.warning("recording rules disabled (bad TPUDASH_RULES): %s", e)
            if self.rule_engine is not None:
                self.tsdb.rule_engine = self.rule_engine
        #: identity of the keys list the rule engine's host map was last
        #: built from (population-keyed cache, one dict build per churn)
        self._rule_host_ref: "object | None" = None
        #: (cache key, {col: [(ts, v), ...]}) for the fleet sparkline query
        self._tsdb_trend_cache: tuple = (None, None)
        if cfg.history_backfill > 0:
            self._backfill_history()
        #: trend persistence (TPUDASH_HISTORY_PATH): restore the rings
        #: unless a Prometheus backfill already seeded them — live range
        #: data beats a snapshot from before the restart
        # cadence arithmetic, not a timestamp: monotonic, so an NTP step
        # can neither force an immediate save nor starve saves for hours
        self._last_history_save = time.monotonic()
        #: serializes snapshot+write: the shutdown save must not lose the
        #: os.replace race to a slower in-flight periodic save (older
        #: snapshot winning the rename)
        self._history_save_lock = threading.Lock()
        if cfg.history_path:
            self._sweep_history_tmp()
            if not self.history:
                self._load_history()
        # one-time legacy migration: whatever primed the rings (the
        # Prometheus backfill or the legacy whole-snapshot history file)
        # seeds the tsdb too, so /api/range and the long sparklines
        # carry that trend from the very first frame — and, with
        # TPUDASH_TSDB_PATH set, it lands in real segments (the old
        # snapshot format stops being the source of truth)
        self._seed_tsdb_from_rings()
        #: threshold alerting over every chip in the table (not just the
        #: selected ones) — see tpudash.alerts
        from tpudash.alerts import AlertEngine, SilenceSet
        from tpudash.hysteresis import DwellSet

        self.alert_engine = AlertEngine.from_config(cfg)
        self.last_alerts: list[dict] = []
        #: anti-flap resolve dwell over the SYNTHESIZED alerts
        #: (endpoint_down / overload / child_down / fleet_partial and the
        #: re-namespaced child digests): once fired, an alert keeps
        #: firing until its condition stays clear for cfg.alert_dwell
        #: seconds — a child flapping at sub-poll period pages once, not
        #: once per flap (TPUDASH_ALERT_DWELL, 0 = off).
        self._synth_dwell = DwellSet(dwell_s=cfg.alert_dwell)
        #: operator acknowledgements: (rule, chip, ttl) silences — flagged
        #: on the frame, excluded from webhook paging, persisted in the
        #: state checkpoint (tpudash.alerts.SilenceSet)
        self.silences = SilenceSet()
        #: set by DashboardServer: () -> dict of per-browser session state
        #: to ride the state checkpoint (the service owns the file, the
        #: server owns the sessions)
        self.sessions_snapshot: "object | None" = None
        #: set by DashboardServer: () -> OverloadGuard.snapshot() — the
        #: serving side's shed/evict state, folded into alert synthesis
        #: (tpudash.app.overload).  None when no server owns this service
        #: (CLI, bench, tests driving the service directly).
        self.overload_provider: "object | None" = None
        items = self._restored_state_doc.get("silences")
        if items:
            # tpulint: allow[wall-clock] silence expiries are epoch stamps
            self.silences = SilenceSet.from_dicts(items, time.time())
        #: fleet outlier scoring every refresh (tpudash.stragglers) — the
        #: chip gating the slice's lockstep step time, named, not just
        #: visible on the heatmap
        from tpudash.stragglers import StragglerDetector

        self.straggler_detector = StragglerDetector.from_config(cfg)
        self.last_stragglers: list[dict] = []
        #: online anomaly detection (tpudash.anomaly): seasonal baseline
        #: deviation + promoted stragglers + torus-correlated ICI fabric
        #: degradation, synthesized as the ``anomaly`` alert rule.  The
        #: incident timeline stitches every alert transition (and
        #: federation child-status flip) into ``GET /api/incidents``.
        from tpudash.anomaly import AnomalyEngine, IncidentTimeline

        self.anomaly_engine = AnomalyEngine.from_config(cfg)
        self.last_anomalies: list[dict] = []
        if (
            self.anomaly_engine is not None
            and self.tsdb is not None
            and self.anomaly_engine.baselines.folds == 0
        ):
            # no persisted baselines: backfill seasonality from the
            # store's 1m/10m rollup quads so a restart scores from the
            # first frame instead of relearning a day of buckets
            seeded = self.anomaly_engine.seed_from_tsdb(self.tsdb)
            if seeded:
                log.info(
                    "seeded anomaly baselines from tsdb rollups "
                    "(%d minute-folds)", seeded,
                )
        if self.rule_engine is not None and self.anomaly_engine is not None:
            # the ``anomaly()`` recording rule: the engine's baseline
            # scorer runs once per sealed chunk and the fleet's worst
            # deviation becomes a persisted __rule__/ series — incident
            # forensics chart it from /api/range instead of replaying
            # raw history through the detector
            self.rule_engine.scorer = self.anomaly_engine.score_series
        self.timeline = IncidentTimeline()
        #: the child side of the registration handshake (PR 15): when
        #: TPUDASH_FEDERATE_ANNOUNCE names parent URLs, a daemon thread
        #: POSTs this node's (id, advertised URL) every ttl/3 so joining
        #: a fleet needs no parent-side config push
        self.announcer = None
        if getattr(cfg, "federate_announce", ""):
            from tpudash.federation.discovery import Announcer
            from tpudash.federation.summary import node_identity

            advertise = getattr(cfg, "federate_advertise", "") or ""
            if not advertise:
                import socket as _socket

                advertise = f"http://{_socket.gethostname()}:{cfg.port}"
            self.announcer = Announcer(
                parents=cfg.federate_announce.split(","),
                name=node_identity(cfg),
                url=advertise,
                auth_token=cfg.auth_token,
                ttl=getattr(cfg, "federate_register_ttl", 60.0) or 60.0,
            )
            self.announcer.start()
        #: (rule, chip) pairs firing in the previous frame — webhook
        #: notifications are sent on transitions only, not every cycle
        self._firing_keys: set = set()
        #: set by the profile endpoint while it replays synthetic renders
        #: (those must never page anyone)
        self.mute_notifications = False
        #: every in-flight webhook delivery thread — a set, not "the latest
        #: one": two back-to-back transitions spawn two deliveries and
        #: flush_webhooks must wait for both
        self._webhook_threads: set = set()

    @property
    def restored_sessions(self) -> dict:
        """The checkpoint's per-browser session section (server restores
        it into its SessionStore at construction)."""
        sessions = self._restored_state_doc.get("sessions")
        return sessions if isinstance(sessions, dict) else {}

    def save_state(self, sessions: "dict | None" = None) -> None:
        """Persist the composite state checkpoint: the anonymous default
        session's UI state, active alert silences, and the per-browser
        cookie-session map — atomically.  One file (cfg.state_path), one
        writer — SelectionState.save wrote only its own keys and would
        drop the rest.

        Blocking disk I/O: the server calls this off the event loop.
        ``sessions`` must then be the snapshot taken ON the loop before
        dispatch — calling the provider from the executor thread would
        iterate the SessionStore while request handlers mutate it."""
        path = self.cfg.state_path
        if not path:
            return
        from tpudash.app.state import atomic_write_json

        doc = self.state.to_dict()
        doc["silences"] = self.silences.to_dicts()
        if sessions is None and self.sessions_snapshot is not None:
            try:
                sessions = self.sessions_snapshot()
            except Exception as e:  # noqa: BLE001 — sessions are best-effort
                log.warning("session snapshot failed: %s", e)
        if sessions is not None:
            doc["sessions"] = sessions
        atomic_write_json(path, doc)

    def _notify_alert_transitions(self) -> None:
        """POST newly-firing and resolved alerts to Config.alert_webhook
        (the pager integration the reference's error banner couldn't be).
        Transition-edge only — a steadily-firing alert posts once.

        Silence semantics (Alertmanager-style): a silenced alert is
        suppressed, not resolved.  Acknowledging a paged alert emits NO
        webhook at all — 'resolved' would close the downstream incident
        while the chip still breaches; a silence expiring mid-fire IS a
        firing transition (it pages again); and an alert that recovers
        while silenced stays suppressed (no late 'resolved' either)."""
        firing = {
            (a["rule"], a["chip"]): a
            for a in self.last_alerts
            if a["state"] == "firing" and not a.get("silenced")
        }
        still_firing_silenced = {
            (a["rule"], a["chip"])
            for a in self.last_alerts
            if a["state"] == "firing" and a.get("silenced")
        }
        fired = [firing[k] for k in firing.keys() - self._firing_keys]
        resolved = sorted(
            self._firing_keys - firing.keys() - still_firing_silenced
        )
        self._firing_keys = set(firing)
        if (
            not self.cfg.alert_webhook
            or self.mute_notifications
            or not (fired or resolved)
        ):
            return
        payload = {
            "source": "tpudash",
            "fired": sorted(fired, key=lambda a: (a["rule"], a["chip"])),
            "resolved": [
                {"rule": rule, "chip": chip} for rule, chip in resolved
            ],
        }
        # deliver OFF the frame path: render_frame runs under the server's
        # frame lock, so a black-holed pager endpoint must not stall every
        # /api/* route for http_timeout seconds
        import threading

        # prune finished deliveries so the set stays bounded over a
        # long-running server, then track the new one
        self._webhook_threads = {
            th for th in self._webhook_threads if th.is_alive()
        }
        t = threading.Thread(
            target=self._deliver_webhook, args=(payload,), daemon=True
        )
        self._webhook_threads.add(t)
        t.start()

    def _deliver_webhook(self, payload: dict) -> None:
        try:
            import requests

            requests.post(
                self.cfg.alert_webhook,
                json=payload,
                timeout=self.cfg.http_timeout,
            ).raise_for_status()
        except Exception as e:  # noqa: BLE001 — notification is best-effort
            log.warning("alert webhook delivery failed: %s", e)

    def flush_webhooks(self, timeout: float = 5.0) -> None:
        """Wait for ALL in-flight webhook deliveries (tests, shutdown),
        sharing one wall-clock budget across them."""
        deadline = time.monotonic() + timeout
        for t in list(self._webhook_threads):
            t.join(max(0.0, deadline - time.monotonic()))
            if not t.is_alive():
                self._webhook_threads.discard(t)

    @contextlib.contextmanager
    def synthetic_load(self):
        """Treat renders inside this block as synthetic load (the profile
        endpoint may burn 100 frames in a second), not monitoring cycles:
        webhooks are muted, alert hysteresis / last-alerts / trend history
        are restored on exit, recording wrappers skip their appends, and
        source-health counters roll back — a replay file, ``/api/alerts``
        and ``/healthz`` must reflect real cycles only."""
        from tpudash.sources.recorder import RecordingSource

        engine = self.alert_engine
        saved_tracks = (
            copy.deepcopy(engine._tracks) if engine is not None else None
        )
        detector = self.straggler_detector
        saved_straggler_tracks = (
            copy.deepcopy(detector._tracks) if detector is not None else None
        )
        saved_stragglers = self.last_stragglers
        saved_anomalies = self.last_anomalies
        saved_alerts = self.last_alerts
        saved_firing = set(self._firing_keys)
        saved_dwell = copy.deepcopy(self._synth_dwell._held)
        # the anomaly engine pauses outright (observe() becomes a no-op:
        # synthetic frames must neither pollute the seasonal baselines
        # nor flap findings) and the incident timeline tells no stories
        # about profile bursts
        anomaly_was_paused = timeline_was_paused = None
        if self.anomaly_engine is not None:
            anomaly_was_paused = self.anomaly_engine.paused
            self.anomaly_engine.paused = True
        timeline_was_paused = self.timeline.paused
        self.timeline.paused = True
        saved_history = list(self.history)
        # /healthz and the error banner serve last_error too: a synthetic
        # render must neither clear a real outage nor leave a fake one
        saved_error = self.last_error
        paused_recorders: list = []
        health_snaps: list = []
        # walk the wrapper chain via instance attrs only (both wrappers
        # define __getattr__ fall-through, so plain getattr would read
        # through to the inner source and loop)
        src, seen = self.source, set()
        while src is not None and id(src) not in seen:
            seen.add(id(src))
            if isinstance(src, RecordingSource) and not src.paused:
                src.paused = True
                paused_recorders.append(src)
            health = src.__dict__.get("health")
            if health is not None and hasattr(health, "snapshot"):
                health_snaps.append((health, health.snapshot()))
            # per-endpoint circuit breakers (MultiSource) roll back too:
            # a burst of profiled frames must not open — or reclose — a
            # breaker the real monitoring cadence owns.  last_errors /
            # _last_fault ride the same rollback so /healthz never
            # serves a synthetic burst's failures as the live state.
            # (_inflight deliberately does NOT roll back: a fetch
            # dispatched under profile is a REAL call against the real
            # endpoint, and forgetting it would re-dispatch a child
            # mid-flight.)
            breakers = src.__dict__.get("breakers")
            if isinstance(breakers, dict):
                for br in breakers.values():
                    if hasattr(br, "snapshot"):
                        health_snaps.append((br, br.snapshot()))
                for attr in ("last_errors", "_last_fault"):
                    d = src.__dict__.get(attr)
                    if isinstance(d, dict):
                        health_snaps.append(
                            (_AttrRestore(src, attr), dict(d))
                        )
            src = src.__dict__.get("inner")
        # the tsdb pauses outright (not save/restore): synthetic frames
        # must not land in PERSISTENT segments, and append_frame itself
        # honors the flag so there is nothing to roll back
        tsdb_was_paused = None
        if self.tsdb is not None:
            tsdb_was_paused = self.tsdb.paused
            self.tsdb.paused = True
        self.mute_notifications = True
        try:
            yield
        finally:
            self.mute_notifications = False
            if tsdb_was_paused is not None:
                self.tsdb.paused = tsdb_was_paused
            for rec in paused_recorders:
                rec.paused = False
            for health, snap in health_snaps:
                health.restore(snap)
            if engine is not None:
                engine._tracks = saved_tracks
            if detector is not None:
                detector._tracks = saved_straggler_tracks
            # /api/alerts must not serve the synthetic renders' inflated
            # streaks until the next real frame
            self.last_alerts = saved_alerts
            self.last_stragglers = saved_stragglers
            self.last_anomalies = saved_anomalies
            if anomaly_was_paused is not None:
                self.anomaly_engine.paused = anomaly_was_paused
            self.timeline.paused = timeline_was_paused
            self._firing_keys = saved_firing
            self._synth_dwell._held = saved_dwell
            self.last_error = saved_error
            self.history.clear()
            self.history.extend(saved_history)

    def _backfill_history(self) -> None:
        """Seed the trend history from the source's range query (Prometheus
        ``query_range``) so sparklines show Config.history_backfill seconds
        of real trend on the very first frame.  Backfilled averages cover
        ALL chips in scope (the live loop averages the *selected* chips);
        failures degrade to an empty history, never a startup crash."""
        fetch_history = getattr(self.source, "fetch_history", None)
        if fetch_history is None:
            return
        # clamp to what the rolling deque can keep: asking for more points
        # than maxlen both wastes the transfer and risks Prometheus's
        # per-series point cap (11k) rejecting the whole range query
        step = max(self.cfg.refresh_interval, 1.0)
        duration = min(
            self.cfg.history_backfill, (self.history.maxlen or 0) * step
        )
        try:
            points = fetch_history(duration, step)
        except Exception as e:  # noqa: BLE001 — backfill is best-effort
            log.warning("history backfill failed: %s", e)
            return
        columns = [p.column for p in (*schema.PANELS, *schema.EXTRA_PANELS)]
        n = 0
        ring_frames: list = []
        for ts, samples in points[-(self.history.maxlen or 0) :]:
            try:
                df = to_wide(samples)
            except Exception:  # noqa: BLE001 — skip malformed slots
                continue
            avgs = {
                col: column_average(df, col) for col in columns if col in df.columns
            }
            if avgs:
                self.history.append((float(ts), avgs))
                ring_frames.append((float(ts), df))
                n += 1
        # Seed the per-chip ring too, so drill-down sparklines carry real
        # trend right after a restart.  Range data is ragged (a metric or
        # chip can be absent at some timestamps), so every point aligns to
        # the UNION of chips/metrics across the window — a series that
        # happens to miss the final step keeps its earlier trend, and
        # missing cells become NaN instead of thrashing the alignment.
        # Best-effort like the rest of backfill: never a startup crash.
        try:
            if ring_frames:
                from tpudash.app.state import _sort_key
                from tpudash.normalize import numeric_columns

                all_keys: dict = {}
                all_cols: dict = {}
                for _, df in ring_frames:
                    for k in df.index:
                        all_keys[k] = None
                    for c in numeric_columns(df):
                        all_cols[c] = None
                # same (slice, chip) order to_wide produces, so a live
                # frame with the same population realigns instead of
                # resetting the ring
                keys = sorted(all_keys, key=_sort_key)
                cols = list(all_cols)
                if cols:
                    self.chip_history.clear()
                    self._chip_hist_keys = keys
                    self._chip_hist_cols = cols
                    self._chip_hist_rowmap = {
                        k: i for i, k in enumerate(keys)
                    }
                    for ts, df in ring_frames:
                        sub = df.reindex(index=keys, columns=cols).apply(
                            pd.to_numeric, errors="coerce"
                        )
                        self.chip_history.append(
                            (ts, sub.to_numpy(dtype=np.float32))
                        )
        except Exception as e:  # noqa: BLE001 — ring seeding is optional
            log.warning("per-chip history backfill failed: %s", e)
            self.chip_history.clear()
            self._chip_hist_keys = []
            self._chip_hist_cols = []
            self._chip_hist_rowmap = {}
        if n:
            log.info(
                "backfilled %d trend points covering %.0f s", n, self.cfg.history_backfill
            )

    def save_history(self) -> None:
        """Snapshot both trend rings to ``cfg.history_path`` (compressed
        npz, atomic replace) — the restart-survival the in-memory deques
        can't offer sources without a Prometheus range query.  The
        snapshot is taken under the publish lock (cheap: list() of ring
        entries); compression runs outside it.  Never raises: trend
        persistence must not take down a refresh or a shutdown."""
        path = self.cfg.history_path
        if not path:
            return
        # the save lock covers snapshot AND write: whoever writes last
        # snapshotted last, so the newest data always wins the rename
        with self._history_save_lock:
            self._save_history_locked(path)

    # _history_save_lock is a DEDICATED I/O-serialization lock (save vs
    # shutdown-save rename ordering); the hot publish lock is held only
    # for the cheap ring snapshot inside.
    # tpulint: allow[blocking-under-lock] dedicated I/O lock, not the publish lock
    def _save_history_locked(self, path: str) -> None:
        import json as _json
        import tempfile

        with self._publish_lock:
            fleet = list(self.history)
            chip_pts = list(self.chip_history)
            keys = list(self._chip_hist_keys)
            cols = list(self._chip_hist_cols)
        if not fleet and not chip_pts:
            return  # nothing learned yet — don't clobber a previous file
        try:
            fcols: list = []
            fpos: dict = {}
            for _, avgs in fleet:
                for c in avgs:
                    if c not in fpos:
                        fpos[c] = len(fcols)
                        fcols.append(c)
            fts = np.array([ts for ts, _ in fleet], dtype=np.float64)
            fdata = np.full((len(fleet), len(fcols)), np.nan, dtype=np.float64)
            for i, (_, avgs) in enumerate(fleet):
                for c, v in avgs.items():
                    fdata[i, fpos[c]] = v
            cts = np.array([ts for ts, _ in chip_pts], dtype=np.float64)
            cdata = (
                np.stack([m for _, m in chip_pts])
                if chip_pts
                else np.zeros((0, 0, 0), dtype=np.float32)
            )
            meta = _json.dumps(
                {"fleet_cols": fcols, "chip_keys": keys, "chip_cols": cols}
            )
            # temp name scoped to the target file so concurrent tpudash
            # instances sharing a directory (distinct history files) can
            # never sweep each other's in-flight save
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(os.path.abspath(path)) or ".",
                prefix=os.path.basename(path) + ".",
                suffix=".tmp",
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez_compressed(
                        f,
                        meta=np.array(meta),
                        fleet_ts=fts,
                        fleet_data=fdata,
                        chip_ts=cts,
                        chip_data=cdata,
                    )
                os.replace(tmp, path)
            except BaseException:
                with contextlib.suppress(OSError):
                    os.unlink(tmp)
                raise
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            log.warning("history save failed: %s", e)

    def _sweep_history_tmp(self) -> None:
        """Remove orphaned ``<history-file>.*.tmp`` siblings of
        history_path — a daemon save thread killed mid-write (process
        exit) never reaches its own unlink, so startup sweeps what
        shutdown couldn't.  The pattern is scoped to THIS instance's
        history file: two instances sharing a directory with distinct
        history files must not delete each other's in-flight saves."""
        import glob

        full = os.path.abspath(self.cfg.history_path)
        d = os.path.dirname(full) or "."
        base = glob.escape(os.path.basename(full))
        for tmp in glob.glob(os.path.join(glob.escape(d), base + ".*.tmp")):
            with contextlib.suppress(OSError):
                os.unlink(tmp)
        # transitional: pre-scoping releases named temps ``tmp*.npz.tmp``;
        # sweep those too, but only when stale (an old-release sibling
        # instance's IN-FLIGHT save is seconds old and must survive)
        import time as _time

        for tmp in glob.glob(os.path.join(glob.escape(d), "tmp*.npz.tmp")):
            with contextlib.suppress(OSError):
                # tpulint: allow[wall-clock] compared against file mtime
                if _time.time() - os.path.getmtime(tmp) > 600.0:
                    os.unlink(tmp)

    def _load_history(self) -> None:
        """Restore the trend rings from ``cfg.history_path``.  Points
        older than twice the ring's time span are dropped (a snapshot
        from last week must not render as if it were the last hour);
        any malformed file degrades to empty rings, never a crash."""
        import json as _json

        path = self.cfg.history_path
        if not os.path.exists(path):
            return
        max_age = (
            (self.history.maxlen or 720)
            * max(self.cfg.refresh_interval, 1.0)
            * 2
        )
        # tpulint: allow[wall-clock] ring points carry persisted epoch ts
        now = time.time()
        cutoff = now - max_age
        # future-timestamped points (snapshot written under a clock that
        # then stepped backward) are dropped too: the refresh-cadence gate
        # compares against the ring's LAST timestamp, so one future point
        # would freeze all new history collection until wall time catches
        # up
        horizon = now + max(self.cfg.refresh_interval, 1.0)
        try:
            with np.load(path) as z:
                meta = _json.loads(str(z["meta"]))
                fleet_ts = z["fleet_ts"]
                fleet_data = z["fleet_data"]
                chip_ts = z["chip_ts"]
                chip_data = z["chip_data"]
            fcols = list(meta["fleet_cols"])
            keys = [str(k) for k in meta["chip_keys"]]
            cols = [str(c) for c in meta["chip_cols"]]
            n = 0
            for ts, row in zip(fleet_ts.tolist(), fleet_data):
                if ts < cutoff or ts > horizon:
                    continue
                avgs = {
                    c: float(v) for c, v in zip(fcols, row.tolist()) if v == v
                }
                if avgs:
                    self.history.append((float(ts), avgs))
                    n += 1
            if (
                keys
                and cols
                and chip_data.ndim == 3
                and chip_data.shape[1:] == (len(keys), len(cols))
            ):
                self._chip_hist_keys = keys
                self._chip_hist_cols = cols
                self._chip_hist_rowmap = {k: i for i, k in enumerate(keys)}
                for ts, m in zip(chip_ts.tolist(), chip_data):
                    if cutoff <= ts <= horizon:
                        self.chip_history.append(
                            (float(ts), m.astype(np.float32, copy=False))
                        )
            if n or self.chip_history:
                log.info(
                    "restored %d fleet / %d per-chip trend points from %s",
                    n,
                    len(self.chip_history),
                    path,
                )
        except Exception as e:  # noqa: BLE001 — restore is best-effort
            log.warning("history restore failed (%s): %s", path, e)
            self.history.clear()
            self.chip_history.clear()
            self._chip_hist_keys = []
            self._chip_hist_cols = []
            self._chip_hist_rowmap = {}

    # -- tsdb (long-horizon compressed trend store) --------------------------
    def _seed_tsdb_from_rings(self) -> None:
        """One-time migration of legacy ring history into the tsdb.
        Runs at startup after the rings were primed (backfill or the
        legacy npz snapshot); skipped when the store already restored
        segments — segment data is newer truth than any snapshot, and
        double-seeding would duplicate points.  Best-effort, never a
        startup crash."""
        tsdb = self.tsdb
        if tsdb is None or (not self.history and not self.chip_history):
            return
        if getattr(tsdb, "read_only", False):
            return  # a follower's truth is the leader's segments
        try:
            if tsdb.stats()["raw_points"]:
                return  # segments already carry history
            from tpudash.tsdb import FLEET_SERIES

            fleet_by_ts = {
                # the store is ms-resolution; key the join the same way
                round(ts, 3): avgs for ts, avgs in self.history
            }
            keys = list(self._chip_hist_keys)
            cols = list(self._chip_hist_cols)
            n = 0
            seen_ts = set()
            for ts, m in self.chip_history:
                avgs = fleet_by_ts.get(round(ts, 3), {})
                fleet_row = np.full((1, len(cols)), np.nan, dtype=np.float32)
                for c, v in avgs.items():
                    if v is None or c not in cols:
                        continue
                    fleet_row[0, cols.index(c)] = v
                tsdb.append_frame(
                    ts, [*keys, FLEET_SERIES], cols, np.vstack([m, fleet_row])
                )
                seen_ts.add(round(ts, 3))
                n += 1
            # fleet-only points (ring reset dropped the chip side)
            for ts, avgs in self.history:
                if round(ts, 3) in seen_ts:
                    continue
                fcols = [c for c, v in avgs.items() if v is not None]
                if not fcols:
                    continue
                row = np.array(
                    [[avgs[c] for c in fcols]], dtype=np.float32
                )
                tsdb.append_frame(ts, [FLEET_SERIES], fcols, row)
                n += 1
            if n and self.cfg.tsdb_path:
                # make the migrated history durable NOW — the legacy
                # snapshot may be gone by the next periodic save
                tsdb.flush(seal_partial=True)
            if n:
                log.info("migrated %d legacy history points into the tsdb", n)
        except Exception as e:  # noqa: BLE001 — migration is best-effort
            log.warning("legacy history migration into tsdb failed: %s", e)

    def _tsdb_ingest(self, now: float, keys, cols, arr32, avgs) -> None:
        """Mirror one ring append into the store: per-chip rows plus the
        FLEET_SERIES pseudo-row carrying the zero-exclusion averages.
        Never fails a frame."""
        tsdb = self.tsdb
        if tsdb is None or getattr(tsdb, "read_only", False):
            return  # a follower never originates data
        try:
            from tpudash.tsdb import FLEET_SERIES

            eng = self.rule_engine
            if eng is not None and self._rule_host_ref is not keys:
                # ``by host`` recording rules need key → host identity;
                # refreshed only on population change (the publish path
                # passes the same keys list object between churns; keys
                # the map misses are simply skipped by the engine)
                df = self.last_df
                if df is not None and "host" in df.columns and len(df):
                    eng.set_host_map(
                        df.index.tolist(), df["host"].tolist()
                    )
                self._rule_host_ref = keys

            if arr32 is not None:
                fleet_row = np.full((1, len(cols)), np.nan, dtype=np.float32)
                pos = {c: i for i, c in enumerate(cols)}
                for c, v in avgs.items():
                    i = pos.get(c)
                    if i is not None and v is not None:
                        fleet_row[0, i] = v
                tsdb.append_frame(
                    now,
                    [*keys, FLEET_SERIES],
                    cols,
                    np.vstack([arr32, fleet_row]),
                )
            else:  # legacy mixed-dtype frames: fleet averages only
                fcols = [c for c, v in avgs.items() if v is not None]
                if fcols:
                    row = np.array(
                        [[avgs[c] for c in fcols]], dtype=np.float32
                    )
                    tsdb.append_frame(now, [FLEET_SERIES], fcols, row)
        except Exception as e:  # noqa: BLE001 — history must not fail frames
            log.warning("tsdb ingest failed: %s", e)

    def _tsdb_trend_series(self, max_points: int) -> "dict | None":
        """Fleet sparkline series from the store — {col: [(ts, v)]} over
        the store's FULL horizon — or None while the in-memory ring is
        the longer record (fresh start, or tests steering the deque
        directly).  Cached per store version: many composes per refresh
        must not re-decode chunks."""
        tsdb = self.tsdb
        if tsdb is None:
            return None
        try:
            from tpudash.tsdb import FLEET_SERIES
            from tpudash.tsdb.query import range_query
            from tpudash.tsdb.rollup import TIERS_MS

            if tsdb.point_count(FLEET_SERIES) <= max(len(self.history), 1):
                return None
            cache_key = (tsdb.version, max_points)
            if self._tsdb_trend_cache[0] == cache_key:
                return self._tsdb_trend_cache[1]
            starts = [tsdb.earliest_ms(t) for t in (0, *TIERS_MS)]
            starts = [s for s in starts if s is not None]
            if not starts:
                return None
            res = range_query(
                tsdb,
                FLEET_SERIES,
                start_s=min(starts) / 1000.0,
                max_points=max_points,
            )
            self._tsdb_trend_cache = (cache_key, res["series"])
            return res["series"]
        except Exception as e:  # noqa: BLE001 — sparklines degrade to the ring
            log.warning("tsdb trend query failed: %s", e)
            return None

    def _tsdb_chip_points(
        self, key: str, max_points: "int | None" = None
    ) -> "list | None":
        """One chip's history from the store as [(ts, {col: v|None})]
        — the long-horizon (and churn-surviving) twin of the per-chip
        ring.  Served through range_query (the one read surface), so the
        window spans EVERY tier (a chip whose raw points expired still
        serves its rollup months), the point budget is a hard ceiling,
        and a wide enough effective step reads the cheap rollup tiers
        instead of decoding the whole raw horizon.  None when the store
        has nothing for the chip."""
        tsdb = self.tsdb
        if tsdb is None:
            return None
        try:
            from tpudash.tsdb.query import DEFAULT_POINTS, range_query
            from tpudash.tsdb.rollup import TIERS_MS

            if not tsdb.series_cols(key):
                return None
            starts = [tsdb.earliest_ms(t) for t in (0, *TIERS_MS)]
            starts = [s for s in starts if s is not None]
            if not starts:
                return None
            budget = (
                max_points
                if max_points is not None
                else max(self.cfg.history_points, DEFAULT_POINTS)
            )
            res = range_query(
                tsdb,
                key,
                start_s=min(starts) / 1000.0,
                max_points=budget,
            )
            cols = list(res["series"])
            by_ts: dict = {}
            for col, pts in res["series"].items():
                for t, v in pts:
                    by_ts.setdefault(t, {})[col] = v if v == v else None
            if not by_ts:
                return None
            return [
                (t, {c: vals.get(c) for c in cols})
                for t, vals in sorted(by_ts.items())
            ]
        except Exception as e:  # noqa: BLE001 — degrade to the ring
            log.warning("tsdb chip query failed for %r: %s", key, e)
            return None

    def close_analysis(self) -> None:
        """Persist the anomaly baselines beside the tsdb segments
        (graceful shutdown; crash loss = at most the unflushed folds)."""
        if self.anomaly_engine is not None:
            self.anomaly_engine.save_baselines()

    def close_announcer(self) -> None:
        """Stop the federation announce heartbeat (graceful shutdown;
        the parent's TTL ages a crashed child out on its own)."""
        if self.announcer is not None:
            self.announcer.stop()

    def close_tsdb(self) -> None:
        """Graceful-shutdown seal: the not-yet-full head chunk compresses
        and (with a path) persists, so a clean restart loses nothing.  A
        crash still loses only the head — by design.  Never raises.
        Cold-tier shutdown rides along: the compactor thread joins (an
        in-flight upload either completes its verify or becomes an
        ignorable husk) and the store handle closes."""
        if self.compactor is not None:
            try:
                self.compactor.close()
            except Exception as e:  # noqa: BLE001 — shutdown must not fail
                log.warning("compactor close failed: %s", e)
        if self.cold is not None:
            try:
                self.cold.close()
            except Exception as e:  # noqa: BLE001 — shutdown must not fail
                log.warning("cold tier close failed: %s", e)
        if self.tsdb is None:
            return
        try:
            self.tsdb.close()
        except Exception as e:  # noqa: BLE001 — shutdown must not fail
            log.warning("tsdb close failed: %s", e)

    def source_health(self) -> "dict | None":
        """Health summary: the ResilientSource wrapper's rolling counters
        plus — for the multi-endpoint join — per-endpoint circuit-breaker
        state (``endpoints``), and — for a federation parent — the
        per-child liveness block (``federation``), so /healthz and the
        frame payload can distinguish "one slice quarantined" / "one
        child dark" from "all sources down".  None when no wrapper or
        join is present."""
        health = getattr(self.source, "health", None)
        summary = health.summary() if health is not None else None
        ep_fn = getattr(self.source, "endpoint_health", None)
        endpoints = ep_fn() if callable(ep_fn) else None
        if endpoints:
            # status derived from the breakers alone (all open → down,
            # any non-closed or mid-streak → degraded)
            states = [e["state"] for e in endpoints.values()]
            if all(s == "open" for s in states):
                ep_status = "down"
            elif any(s != "closed" for s in states) or any(
                e["consecutive_failures"] > 0 for e in endpoints.values()
            ):
                ep_status = "degraded"
            else:
                ep_status = "healthy"
            summary = self._fold_health(summary, ep_status)
            summary["endpoints"] = endpoints
        fs = self._federation_summary()
        if fs and fs["children_total"]:
            # child liveness folds exactly like endpoint breakers: every
            # child dark = down (nothing left to serve), any child not
            # live = degraded — while ``ok`` upstream stays true (the
            # PARENT process is alive and serving last-good data)
            if fs["children_dark"] == fs["children_total"]:
                fed_status = "down"
            elif fs["partial"]:
                fed_status = "degraded"
            else:
                fed_status = "healthy"
            summary = self._fold_health(summary, fed_status)
            summary["federation"] = fs
        return summary

    @staticmethod
    def _fold_health(summary: "dict | None", status: str) -> dict:
        """Merge a join-level verdict into the wrapper's summary: the
        retry wrapper only sees whole-fetch outcomes, and a partial
        multi/federated fetch SUCCEEDS — its "healthy" must not mask a
        quarantined endpoint or a dark child; the worse verdict wins."""
        if summary is None:
            return {"status": status}
        rank = {"healthy": 0, "degraded": 1, "down": 2}
        summary = dict(summary)
        if rank.get(status, 0) > rank.get(summary.get("status"), 0):
            summary["status"] = status
        return summary

    def _federation_summary(self) -> "dict | None":
        """The source's federation block, or None off the federation
        path.  Read-through (the source snapshots under its own lock);
        failures degrade to None — observability must not fail frames."""
        fed_fn = getattr(self.source, "federation_summary", None)
        if not callable(fed_fn):
            return None
        try:
            return fed_fn()
        except Exception as e:  # noqa: BLE001 — observability is best-effort
            log.warning("federation summary failed: %s", e)
            return None

    def summary_doc(self, binary: bool = False) -> dict:
        """The compact ``/api/summary`` document a federation parent
        polls (tpudash.federation.summary.build_summary) — per-chip
        latest columns, fleet rollup, alert digest, health.  Blocking
        (matrix serialization): the server builds it in the executor.
        ``binary`` keeps the matrix as the float64 block for the TDB1
        encoding instead of materializing JSON cells."""
        from tpudash.federation.summary import build_summary

        with self._publish_lock:
            return build_summary(self, binary=binary)

    def _federation_alerts(self, now: float) -> "list[dict]":
        """The hierarchical alert rollup: synthesized ``child_down`` per
        degraded child and ``fleet_partial`` for the pane, plus every
        reachable child's own alerts re-namespaced into the parent's
        alert space — all shaped like AlertEngine output so silences,
        the webhook pager, and the banner treat a dark cluster exactly
        like a breaching chip."""
        fs = self._federation_summary()
        if not fs:
            return []
        from tpudash.alerts import synthesized_alert

        out: "list[dict]" = []
        degraded: "list[str]" = []
        for name, c in sorted(fs["children"].items()):
            br = c.get("breaker") or {}
            status = c.get("status")
            if status != "live":
                degraded.append(name)
            if c.get("cycle"):
                # a child whose summary already aggregates THIS parent:
                # the distinct LOUD page — a cycle is an operator
                # topology error, not a partition, and the runbook
                # actions differ (break the loop vs chase the network)
                out.append(
                    synthesized_alert(
                        rule="federation_cycle",
                        column="federation",
                        severity="critical",
                        chip=name,
                        value=1.0,
                        threshold=0.0,
                        firing=True,
                        streak=int(br.get("consecutive_failures") or 1),
                        detail=c["cycle"],
                        child_status=status,
                    )
                )
                continue  # child_down would double-page the same cause
            firing = status == "dark" or br.get("state") in (
                "open",
                "half_open",
            )
            if (
                not firing
                and status == "live"
                and not br.get("consecutive_failures")
            ):
                continue
            open_for = br.get("open_for_s")
            out.append(
                synthesized_alert(
                    rule="child_down",
                    column="federation",
                    severity="critical",
                    chip=name,
                    value=float(br.get("consecutive_failures") or 0),
                    threshold=float(br.get("failure_threshold") or 0),
                    firing=firing,
                    since=(
                        round(now - open_for, 3)
                        if firing and open_for is not None
                        else None
                    ),
                    streak=int(br.get("consecutive_failures") or 0),
                    # the parent-side fault when there is one, else the
                    # child's own error (an answering-but-empty child
                    # fails with a child-side cause, not a network one)
                    detail=c.get("last_error") or c.get("child_error"),
                    breaker=br.get("state"),
                    child_status=status,
                    staleness_s=c.get("staleness_s"),
                )
            )
        # nested degradation (PR 15): a grandchild partition two levels
        # down surfaces HERE with its exact subtree path — the per-level
        # stale/dark sets the recursive fan-in folded upward
        subtrees: "list[str]" = []
        for i, lvl in enumerate(fs.get("levels") or []):
            if i == 0:
                continue  # direct children already named above
            subtrees.extend(lvl.get("stale") or [])
            subtrees.extend(lvl.get("dark") or [])
        if degraded or subtrees:
            k, n = len(degraded), fs["children_total"]
            parts = []
            if degraded:
                parts.append(
                    f"{k}/{n} federated children degraded "
                    f"({', '.join(degraded)})"
                )
            if subtrees:
                parts.append(
                    "degraded subtrees: " + ", ".join(sorted(subtrees))
                )
            out.append(
                synthesized_alert(
                    rule="fleet_partial",
                    column="federation",
                    severity="warning",
                    chip="fleet",
                    value=float(k + len(subtrees)),
                    threshold=0.0,
                    firing=True,
                    streak=max(1, k),
                    detail=(
                        "; ".join(parts) + " — the fleet frame is "
                        "partial: last-good data serving where available"
                    ),
                )
            )
        alerts_fn = getattr(self.source, "federated_alerts", None)
        if callable(alerts_fn):
            try:
                out += alerts_fn()
            except Exception as e:  # noqa: BLE001 — rollup is best-effort
                log.warning("federated alert rollup failed: %s", e)
        return out

    def _endpoint_alerts(self, now: float) -> list[dict]:
        """Synthesized ``endpoint_down`` alert entries from the breaker
        states — one per unhealthy endpoint, shaped like AlertEngine
        output so silences, the webhook pager, and the banner treat a
        quarantined slice exactly like a breaching chip.  Open/half-open
        breakers fire; a closed breaker mid-streak is pending."""
        from tpudash.alerts import synthesized_alert

        ep_fn = getattr(self.source, "endpoint_health", None)
        if not callable(ep_fn):
            return []
        out = []
        for label, s in ep_fn().items():
            if s["state"] == "closed" and s["consecutive_failures"] == 0:
                continue
            firing = s["state"] in ("open", "half_open")
            open_for = s.get("open_for_s")
            out.append(
                synthesized_alert(
                    rule="endpoint_down",
                    column="endpoint",
                    severity="critical",
                    chip=label,
                    value=float(s["consecutive_failures"]),
                    threshold=float(s["failure_threshold"]),
                    firing=firing,
                    since=(
                        round(now - open_for, 3)
                        if firing and open_for is not None
                        else None
                    ),
                    streak=s["consecutive_failures"],
                    detail=s.get("last_error"),
                    breaker=s["state"],
                )
            )
        return out

    def _overload_alerts(self, now: float) -> list[dict]:
        """Synthesized ``overload`` alert from the server's admission
        guard — shaped like AlertEngine output (same contract as
        ``endpoint_down``), so a dashboard shedding load pages the
        webhook and shows on the banner like any other incident.
        Shedding is a warning; a gate running full (saturated) is
        critical.  Runs on the refresh executor thread: the guard's
        snapshot() is read-only and thread-safe by design."""
        provider = self.overload_provider
        if provider is None:
            return []
        from tpudash.alerts import synthesized_alert

        try:
            snap = provider()
        except Exception as e:  # noqa: BLE001 — observability is best-effort
            log.warning("overload snapshot failed: %s", e)
            return []
        state = snap.get("state")
        if state in (None, "normal"):
            return []
        recent = int(snap.get("recent_sheds", 0))
        return [
            synthesized_alert(
                rule="overload",
                column="server",
                severity="critical" if state == "saturated" else "warning",
                chip="server",
                value=float(recent),
                threshold=0.0,
                firing=True,
                since=round(now - float(snap.get("since_s", 0.0)), 3),
                streak=recent,
                detail=(
                    f"server {state}: {recent} requests shed in the "
                    f"shed window (inflight {snap.get('inflight')}, "
                    f"streams {snap.get('streams')}, "
                    f"total shed {snap.get('total_shed')})"
                ),
                overload=state,
            )
        ]

    def _cold_alerts(self, now: float) -> "list[dict]":
        """Synthesized cold-tier alerts (AlertEngine output shape, same
        contract as ``endpoint_down``): ``cold_unreachable`` (warning)
        while the object store is dark — range answers degrade to the
        hot horizon flagged ``partial: true`` and segment reclaim is
        paused, the dashboard itself is healthy — and ``cold_corrupt``
        (critical) while quarantined bundles exist, because archived
        history is silently missing until re-compaction heals them.
        Runs on the refresh executor thread; status() is lock-cheap."""
        cold = self.cold
        if cold is None:
            return []
        from tpudash.alerts import synthesized_alert

        try:
            st = cold.status()
        except Exception as e:  # noqa: BLE001 — observability is best-effort
            log.warning("cold status failed: %s", e)
            return []
        out = []
        if st["unreachable"]:
            out.append(
                synthesized_alert(
                    rule="cold_unreachable",
                    column="tsdb",
                    severity="warning",
                    chip="cold-store",
                    value=1.0,
                    threshold=0.0,
                    firing=True,
                    detail=(
                        f"object store unreachable ({st['store']}): "
                        f"{st['last_error']} — range answers degrade to "
                        "the hot horizon (partial:true), segment reclaim "
                        "paused until the store heals"
                    ),
                )
            )
        if st["quarantined"]:
            out.append(
                synthesized_alert(
                    rule="cold_corrupt",
                    column="tsdb",
                    severity="critical",
                    chip="cold-store",
                    value=float(st["quarantined"]),
                    threshold=0.0,
                    firing=True,
                    detail=(
                        "quarantined archive bundle(s), never served: "
                        + ", ".join(st["quarantined_keys"])
                        + " — re-compaction heals them while sources "
                        "exist (runbook: docs/OPERATIONS.md, cold tier)"
                    ),
                )
            )
        return out

    def _anomaly_alerts(self) -> "list[dict]":
        """The anomaly engine's current synthesized entries (rule
        ``anomaly``, AlertEngine output shape plus kind/score/evidence).
        The engine rebuilds them each observe(); error cycles serve the
        last computed set — "not evaluated" is not "recovered"."""
        if self.anomaly_engine is None:
            return []
        return list(self.anomaly_engine.alert_entries)

    # -- panel helpers -------------------------------------------------------
    def _active_panels(self, df: pd.DataFrame) -> list[schema.PanelSpec]:
        """The reference's fixed four panels plus TPU extras whose series
        the source actually provides."""
        panels = [p for p in schema.PANELS if p.column in df.columns]
        panels += [p for p in schema.EXTRA_PANELS if p.column in df.columns]
        return panels

    def _average_row(
        self, sel_df: pd.DataFrame, panels, use_gauge: bool, avgs: dict
    ) -> dict:
        accels = accel_types_for(sel_df)
        figures = []
        for spec in panels:
            avg = avgs.get(spec.column)
            value = 0.0 if avg is None else avg  # reference renders 0 on empty
            figures.append(
                {
                    "panel": spec.column,
                    "figure": create_visualization(
                        value,
                        spec,
                        use_gauge=use_gauge,
                        height=self.cfg.avg_panel_height,
                        accel_types=accels,
                        title=f"Avg {spec.title}",
                    ),
                }
            )
        return {"title": "Average (selected chips)", "figures": figures}

    def _device_rows(self, sel_df: pd.DataFrame, panels, use_gauge: bool) -> list:
        rows = []
        for key, row in sel_df.iterrows():
            accel = row.get(schema.ACCEL_TYPE, "")
            figures = []
            for spec in panels:
                value = row.get(spec.column)
                if value is None or pd.isna(value):
                    continue
                figures.append(
                    {
                        "panel": spec.column,
                        "figure": create_visualization(
                            float(value),
                            spec,
                            use_gauge=use_gauge,
                            height=self.cfg.device_panel_height,
                            accel_types=[accel] if accel else None,
                        ),
                    }
                )
            rows.append(
                {
                    # header parity: "### GPU {id} ({model})" app.py:415
                    "title": f"TPU {row['chip_id']} ({_model_name(accel)})",
                    "key": key,
                    "figures": figures,
                }
            )
        return rows

    def _heatmaps(
        self, sel_df: pd.DataFrame, df: pd.DataFrame, panels, block=None
    ) -> list:
        """One heatmap per panel metric, per slice, over selected chips.

        Pure-numpy grouping: the old groupby/boolean-mask version copied
        the full mixed-dtype frame twice per slice (~8 ms/frame at 256
        chips); this touches only the identity arrays and the shared
        numeric block."""
        out = []
        arr, cols = block if block is not None else dense_block(sel_df)
        col_pos = {c: i for i, c in enumerate(cols)}
        # identity arrays come from the shared per-refresh extraction; the
        # select-all fast path (filter_selected returns df itself) reuses
        # them for the selection side too
        ident_ok = (
            self._ident_slices is not None
            and len(self._ident_slices) == len(df)
        )
        if ident_ok:
            all_slices = self._ident_slices
            all_chips = self._ident_chips
            all_keys = self._ident_keys
        else:  # compose without a matching refresh (direct test calls)
            all_slices = df["slice_id"].to_numpy()
            all_chips = df["chip_id"].to_numpy()
            all_keys = df.index.to_numpy()
        if sel_df is df and ident_ok:
            sel_slices, sel_chips = all_slices, all_chips
            sel_accels = np.asarray(self._ident_accels, dtype=object)
        else:
            sel_slices = sel_df["slice_id"].to_numpy()
            sel_chips = sel_df["chip_id"].to_numpy()
            sel_accels = (
                sel_df[schema.ACCEL_TYPE].fillna("").to_numpy()
                if schema.ACCEL_TYPE in sel_df
                else None
            )
        # per-slice GEOMETRY (group indices, topology, clickable key
        # grids, range masks) is a pure population/selection function —
        # cached across ticks for the select-all frame; only the z-value
        # scatter runs per tick.  Partial selections build fresh.
        cacheable = (
            sel_df is df and ident_ok and df.index is self._ident_index
        )
        geo = self._heatmap_geo if cacheable else None
        if geo is None:
            geo = []
            codes, uniques = pd.factorize(sel_slices, sort=True)
            everything = len(sel_df) == len(df)  # select-all fast path
            for g, slice_id in enumerate(uniques):
                if len(uniques) == 1:
                    sel_idx = np.arange(len(sel_df))
                else:
                    sel_idx = np.nonzero(codes == g)[0]
                if everything and len(uniques) == 1:
                    all_ids, a_keys = all_chips, all_keys
                else:
                    amask = all_slices == slice_id
                    all_ids, a_keys = all_chips[amask], all_keys[amask]
                if sel_accels is not None:
                    accels = sorted({a for a in sel_accels[sel_idx] if a})
                else:
                    accels = []
                generation = accels[0] if accels else self.cfg.generation
                # topology sized to the FULL slice population (not just
                # the selection) so partial selections keep real torus
                # coordinates.  Bogus ids (negative, or beyond any real
                # pod size — v5p tops out near 9k chips) are excluded
                # from sizing AND rendering: per-series tolerance
                # (sources/base.py), a corrupt series drops its cell, it
                # must not size a 2e9-cell grid or raise.
                sane = all_ids[(all_ids >= 0) & (all_ids < 16384)]
                if sane.size == 0:
                    continue
                n = int(sane.max()) + 1
                topo = topology_for(generation, n)
                chip_ids = sel_chips[sel_idx]
                in_range = (chip_ids >= 0) & (chip_ids < topo.num_chips)
                # clickable cells: keys come from the FULL slice
                # population so a deselected chip can be clicked back on
                # (symmetric toggle), built once per slice and shared by
                # every panel's figure
                ok = (all_ids >= 0) & (all_ids < topo.num_chips)
                # .tolist() yields native ints/strs in one C pass (a
                # per-cell int()/str() genexpr was ~1 ms/frame @256)
                custom_grid = key_grid(
                    topo,
                    dict(zip(all_ids[ok].tolist(), a_keys[ok].tolist())),
                )
                # batched-scatter geometry: grid positions for the
                # selection's in-range chips, plus whether they densely
                # cover the grid (no gap cells → pure float z rows)
                from tpudash.topology import _flat_positions, grid_layout

                gny, gwidth, _cells = grid_layout(topo)
                pos = _flat_positions(topo)[chip_ids[in_range]]
                covered = np.zeros(gny * gwidth, dtype=bool)
                covered[pos] = True
                dense = bool(covered.all()) and bool(in_range.all())
                geo.append(
                    (slice_id, sel_idx, chip_ids, in_range, accels,
                     topo, custom_grid, pos, dense, (gny, gwidth))
                )
            if cacheable:
                self._heatmap_geo = geo
        for (slice_id, sel_idx, chip_ids, in_range, accels, topo,
             custom_grid, pos, dense, (gny, gwidth)) in geo:
            rounded_sub = nan_sub = None
            zall = None
            if arr is not None:
                # one slice-sized extraction + round + isnan for ALL
                # panels (per-panel column ops were ~2 ms/frame at 96
                # slice×panel grids).  2dp: hover shows 1dp, so nothing
                # visible is lost and the z-matrix wire cost drops ~3x
                # (17-char doubles → "53.33")
                pcols = [
                    col_pos[s.column] for s in panels if s.column in col_pos
                ]
                rounded_sub = np.round(arr[sel_idx][:, pcols], 2)
                nan_sub = np.isnan(rounded_sub)
                sub_j = {c: j for j, c in enumerate(pcols)}
                if dense and not nan_sub.any():
                    # fully-populated slice (the scale-dominant shape):
                    # ONE scatter and ONE tolist materialize every
                    # panel's z grid — 6 numpy round-trips per slice
                    # collapse to 1
                    grids = np.empty((len(pcols), gny * gwidth))
                    grids[:, pos] = rounded_sub.T  # in_range is all-True here
                    zall = grids.reshape(len(pcols), gny, gwidth).tolist()
            for spec in panels:
                ci = col_pos.get(spec.column)
                if ci is None:
                    if arr is not None or spec.column not in sel_df.columns:
                        continue
                if zall is not None:
                    grid = zall[sub_j[ci]]
                elif arr is not None:
                    vals = rounded_sub[:, sub_j[ci]]
                    mask = ~nan_sub[:, sub_j[ci]] & in_range
                    ids_on = chip_ids[mask]
                    if ids_on.size == 0:
                        continue
                    grid = heatmap_grid_arrays(topo, ids_on, vals[mask])
                else:  # legacy mixed-dtype frames
                    vals = pd.to_numeric(
                        sel_df[spec.column].iloc[sel_idx], errors="coerce"
                    ).to_numpy(dtype=float, na_value=np.nan)
                    vals = np.round(vals, 2)
                    mask = ~np.isnan(vals) & in_range
                    ids_on = chip_ids[mask]
                    if ids_on.size == 0:
                        continue
                    grid = heatmap_grid_arrays(topo, ids_on, vals[mask])
                out.append(
                    {
                        "panel": spec.column,
                        "slice": str(slice_id),
                        "figure": create_topology_heatmap(
                            topo,
                            None,
                            title=f"{slice_id} — {spec.title}",
                            max_val=panel_max(spec, accels),
                            unit=spec.unit,
                            custom_grid=custom_grid,
                            grid=grid,
                        ),
                    }
                )
        return out

    def _breakdown(self, sel_df: pd.DataFrame, panels, block=None) -> dict:
        """Per-slice and per-host averages over the selection — the fleet
        drill-down the reference's flat per-GPU list couldn't offer.  A
        dimension appears only when it actually distinguishes rows (>1
        distinct value).  Averages use the same zero-exclusion policy as
        the headline row."""
        cols = [p.column for p in panels if p.column in sel_df.columns]
        if not cols:
            return {}
        # factorize each dimension ONCE (also the degenerate-case gate):
        # the common single-slice single-host frame skips the matrix prep
        # entirely.  Rows whose group label is missing (factorize code -1,
        # e.g. a joined source without the host label) are excluded from
        # that dimension rather than corrupting a group.
        # group codes are pure population functions — cached across ticks
        # for the select-all frame (invalidated by publish on population
        # change); partial selections factorize fresh
        cacheable = sel_df.index is self._ident_index
        dims = self._group_cache if cacheable else None
        if dims is None:
            dims = []
            for dim, col in (("by_slice", "slice_id"), ("by_host", "host")):
                if col not in sel_df.columns:
                    continue
                # factorize the raw object ndarray: the Series path
                # detours through arrow string conversion on this build
                codes, uniques = pd.factorize(
                    sel_df[col].to_numpy(dtype=object), sort=True
                )
                if len(uniques) > 1:
                    dims.append((dim, codes, uniques))
            if cacheable:
                self._group_cache = dims
        if not dims:
            return {}
        # pure-numpy group means (factorize + add.at), not groups×columns
        # column_average calls or pandas groupby machinery — at 256 chips
        # the host dimension alone has 64+ groups and this runs per frame.
        # The numeric matrix comes from the shared per-frame block when the
        # caller already extracted it (copy: zero-exclusion mutates cells).
        blk_arr, blk_cols = (
            block if block is not None else (None, [])
        )
        if blk_arr is not None and all(c in blk_cols for c in cols):
            pos = [blk_cols.index(c) for c in cols]
            arr = blk_arr[:, pos].copy()
        else:
            sub = sel_df[cols]
            if all(dt.kind in "fi" for dt in sub.dtypes):
                arr = sub.to_numpy(dtype=np.float64, copy=True)
            else:  # legacy mixed-dtype frames
                arr = sub.apply(pd.to_numeric, errors="coerce").to_numpy(
                    dtype=np.float64, copy=True
                )
        for i, column in enumerate(cols):
            # zero-exclusion becomes NaN-exclusion (app.py:341-345 policy)
            if column in schema.ZERO_EXCLUDED_METRICS:
                arr[arr[:, i] == 0.0, i] = np.nan
        valid = ~np.isnan(arr)
        filled = np.where(valid, arr, 0.0)

        out: dict = {}
        for dim, codes, uniques in dims:
            labeled = codes >= 0  # drop rows with a missing group label
            if labeled.all():
                lcodes, lfilled, lvalid = codes, filled, valid
            else:
                lcodes = codes[labeled]
                lfilled = filled[labeled]
                lvalid = valid[labeled]
            G = len(uniques)
            # per-column bincount: same accumulation (input order) as the
            # old np.add.at scatter but ~20x faster — add.at alone was
            # ~4 ms/frame at 1,024 host groups
            sums = np.empty((G, len(cols)))
            counts = np.empty((G, len(cols)))
            for i in range(len(cols)):
                sums[:, i] = np.bincount(
                    lcodes, weights=lfilled[:, i], minlength=G
                )
                counts[:, i] = np.bincount(
                    lcodes, weights=lvalid[:, i], minlength=G
                )
            with np.errstate(invalid="ignore"):
                means = np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)
            sizes = np.bincount(lcodes, minlength=G)
            # one vectorized round + one C-pass .tolist(): the per-cell
            # round(float(...)) genexpr was ~10k Python-level calls per
            # frame at 1,024 host groups (the 4,096-chip profile's
            # second-largest Python cost after the native parse)
            rounded = np.round(means, 2).tolist()
            # row dicts: dict(zip) C-path when the row is fully valid
            # (the overwhelmingly common shape), per-cell only with NaNs
            full_row = (~np.isnan(means)).all(axis=1).tolist()
            sizes_l = sizes.tolist()
            rows: dict = {}
            for g, key in enumerate(uniques):
                rv = rounded[g]
                if full_row[g]:
                    vals = dict(zip(cols, rv))
                else:
                    vals = {
                        c: rv[i]
                        for i, c in enumerate(cols)
                        if rv[i] == rv[i]  # drop no-eligible-value cols
                    }
                if vals:
                    vals["chips"] = sizes_l[g]
                    rows[str(key)] = vals
            if rows:
                out[dim] = rows
        return out

    def _trends(self, sel_df: pd.DataFrame, panels, max_points: int = 120) -> list:
        """Sparkline per panel over the fleet-average trend, ≤max_points.

        Two sources, one contract: once the tsdb holds a longer fleet
        record than the in-memory ring (restart with segments, or simply
        outliving the ring's maxlen) the series comes from the STORE via
        the range-query layer — full horizon, step-aligned means; until
        then the ring serves, downsampled with the stride anchored at
        the newest point."""
        accels = accel_types_for(sel_df)
        # trends are selection-independent (fleet averages) and the
        # underlying series advance only on refresh: every compose of the
        # same data tick (N cohorts per tick) reuses one build — at 4,096
        # chips the store read + strftime of 6 panels was ~4 ms/compose
        cache_key = (
            self.last_updated_ts,
            len(self.history),
            max_points,
            tuple(p.column for p in panels),
            tuple(accels),
        )
        if self._trend_cache is not None and self._trend_cache[0] == cache_key:
            return self._trend_cache[1]
        store_series = self._tsdb_trend_series(max_points)
        if store_series is None and len(self.history) < 2:
            return []
        if store_series is not None:
            fmt = None

            def col_series(col):
                return store_series.get(col, [])

        else:
            pts, fmt = _downsample(list(self.history), max_points)

            def col_series(col):
                return [
                    (ts, avgs[col])
                    for ts, avgs in pts
                    if avgs.get(col) is not None
                ]

        out = []
        strf_memo = self._strftime_cache
        if len(strf_memo) > 8192:
            strf_memo.clear()
        for spec in panels:
            series = col_series(spec.column)
            if len(series) < 2:
                continue
            if fmt is None:
                # memoized per timestamp: the panels share one time grid,
                # and consecutive ticks share most of it
                times = []
                for ts, _ in series:
                    t = strf_memo.get(ts)
                    if t is None:
                        t = strf_memo[ts] = _dt.datetime.fromtimestamp(
                            ts
                        ).strftime("%H:%M:%S")
                    times.append(t)
            else:
                times = [fmt[ts] for ts, _ in series]
            out.append(
                {
                    "panel": spec.column,
                    "figure": create_sparkline(
                        times,
                        [v for _, v in series],
                        title=f"{spec.title} — trend",
                        max_val=panel_max(spec, accels),
                        unit=spec.unit,
                    ),
                }
            )
        self._trend_cache = (cache_key, out)
        return out

    def chip_detail(
        self,
        key: str,
        use_gauge: bool = True,
        max_points: int = 200,
    ) -> "dict | None":
        with self._publish_lock:
            return self._chip_detail_locked(key, use_gauge, max_points)

    def _chip_detail_locked(
        self,
        key: str,
        use_gauge: bool = True,
        max_points: int = 200,
    ) -> "dict | None":
        """Single-chip drill-down: identity, current panel gauges, per-chip
        trend sparklines, its firing alerts, and its ICI neighbors — the
        per-device insight of the reference's gauge rows (app.py:411-476)
        restored at 256-chip scale, one chip at a time.  None when the chip
        is not in the last table (404 upstream)."""
        df = self.last_df
        if df is None or key not in df.index:
            return None
        row = df.loc[key]
        accel = row.get(schema.ACCEL_TYPE, "") or ""
        panels = self._active_panels(df)
        figures = []
        for spec in panels:
            value = row.get(spec.column)
            if value is None or pd.isna(value):
                continue
            figures.append(
                {
                    "panel": spec.column,
                    "figure": create_visualization(
                        float(value),
                        spec,
                        use_gauge=use_gauge,
                        height=self.cfg.device_panel_height,
                        accel_types=[accel] if accel else None,
                    ),
                }
            )
        # per-chip sparklines: the tsdb serves once it holds a longer
        # record for this chip than the ring (same contract as _trends);
        # the ring covers fresh starts and store-less configs
        trends = []
        hist_row = self._chip_hist_rowmap.get(key)
        ring_len = len(self.chip_history) if hist_row is not None else 0
        store_pts = None
        if self.tsdb is not None:
            try:
                if self.tsdb.point_count(key) > max(ring_len, 1):
                    store_pts = self._tsdb_chip_points(key, max_points)
            except Exception:  # noqa: BLE001 — degrade to the ring
                store_pts = None
        if store_pts:

            def spec_series(column):
                return [
                    (ts, vals[column])
                    for ts, vals in store_pts
                    if vals.get(column) is not None
                ]

        elif hist_row is not None and len(self.chip_history) >= 2:
            pts, _fmt = _downsample(list(self.chip_history), max_points)
            col_pos = {c: i for i, c in enumerate(self._chip_hist_cols)}

            def spec_series(column):
                ci = col_pos.get(column)
                if ci is None:
                    return []
                return [
                    (ts, float(m[hist_row, ci]))
                    for ts, m in pts
                    if m[hist_row, ci] == m[hist_row, ci]  # skip NaN
                ]

        else:
            spec_series = None
        if spec_series is not None:
            for spec in panels:
                series = spec_series(spec.column)
                if len(series) < 2:
                    continue
                trends.append(
                    {
                        "panel": spec.column,
                        "figure": create_sparkline(
                            [
                                _dt.datetime.fromtimestamp(ts).strftime(
                                    "%H:%M:%S"
                                )
                                for ts, _ in series
                            ],
                            [v for _, v in series],
                            title=f"{spec.title} — chip trend",
                            max_val=panel_max(
                                spec, [accel] if accel else None
                            ),
                            unit=spec.unit,
                        ),
                    }
                )
        # torus neighbors = the chips it shares ICI links with
        try:
            neighbors = torus_neighbor_keys(df, key, self.cfg.generation)
        except Exception:  # noqa: BLE001 — neighbors are best-effort context
            neighbors = []
        # direction-resolved link table (sources with per-link series):
        # each physical cable's measured GB/s + the chip on its far end,
        # flagged when straggler detection names that link
        try:
            links = chip_links(df, key, self.cfg.generation)
        except Exception:  # noqa: BLE001 — link detail is best-effort too
            links = []
        if links:
            flagged = {
                s["link"]
                for s in self.last_stragglers
                if s.get("chip") == key and "link" in s
            }
            for entry in links:
                entry["straggler"] = entry["dir"] in flagged
        return {
            "key": key,
            "chip_id": int(row["chip_id"]),
            "slice": str(row["slice_id"]),
            "host": str(row.get("host", "")),
            "model": _model_name(accel),
            "accelerator_type": accel,
            "figures": figures,
            "trends": trends,
            "alerts": [a for a in self.last_alerts if a.get("chip") == key],
            "stragglers": [
                s for s in self.last_stragglers if s.get("chip") == key
            ],
            "neighbors": neighbors,
            "links": links,
            "last_updated": self.last_updated,
        }

    def chip_series(self, key: str) -> "list[tuple[float, dict]] | None":
        """One chip's raw history as [(ts, {column: value-or-None}), ...]
        — /api/history?chip= serves this verbatim.  Served from the tsdb
        once it holds a longer record than the per-chip ring (restart
        with segments, outliving the ring's maxlen, or a chip that
        churned OUT of the ring's population — the store keeps serving
        departed chips); the ring covers the rest.  Returns None for a
        chip neither tier has seen."""
        with self._publish_lock:
            return self._chip_series_locked(key)

    def _chip_series_locked(self, key: str):
        row = self._chip_hist_rowmap.get(key)
        ring_len = len(self.chip_history) if row is not None else 0
        tsdb = self.tsdb
        if tsdb is not None:
            try:
                longer = tsdb.point_count(key) > ring_len
            except Exception:  # noqa: BLE001 — degrade to the ring
                longer = False
            if longer:
                pts = self._tsdb_chip_points(key)
                if pts:
                    return pts
        if row is None:
            return None
        cols = list(self._chip_hist_cols)
        out = []
        for ts, m in self.chip_history:
            vals = m[row].tolist()
            out.append(
                (ts, {c: (v if v == v else None) for c, v in zip(cols, vals)})
            )
        return out

    def topology_model(self) -> "dict | None":
        """The fleet's torus model — per slice: generation, dims, and per
        chip: key, torus coordinates, and ICI neighbor ids.  What external
        tooling (wiring diagrams, placement planners) needs and the
        heatmap only carries implicitly.  None before the first frame."""
        with self._publish_lock:
            df = self.last_df
            if df is None:
                return None
            slices = []
            for slice_id, same in df.groupby("slice_id", sort=True):
                ids = same["chip_id"].to_numpy()
                sane = ids[(ids >= 0) & (ids < 16384)]
                if sane.size == 0:
                    continue
                accels = accel_types_for(same)
                generation = accels[0] if accels else self.cfg.generation
                topo = topology_for(generation, int(sane.max()) + 1)
                chips = [
                    {
                        "key": str(k),
                        "chip_id": int(c),
                        "coords": list(topo.coords(int(c))),
                        "neighbors": topo.neighbors(int(c)),
                        # direction-labeled far ends ("x+" → chip_id):
                        # which cable reaches which neighbor
                        "links": {
                            schema.ICI_LINK_LABELS[d]: nid
                            for d, nid in topo.directed_neighbors(int(c))
                        },
                    }
                    for k, c in zip(same.index.tolist(), ids.tolist())
                    if 0 <= c < topo.num_chips
                ]
                slices.append(
                    {
                        "slice": str(slice_id),
                        "generation": topo.generation,
                        "dims": list(topo.dims),
                        "num_chips": topo.num_chips,
                        "reporting_chips": len(chips),
                        "chips": chips,
                    }
                )
            return {"slices": slices}

    # -- the frame -----------------------------------------------------------
    def refresh_data(self) -> "pd.DataFrame | None":
        """Scrape → normalize → alerts → trend history: the shared half of
        a frame, run ONCE per refresh interval no matter how many viewer
        sessions compose frames from it.  Returns the wide table, or None
        when the source failed (``last_error`` carries the banner text —
        the reference's error path, app.py:225-227).

        The timer frame opened here is completed by the first
        :meth:`compose_frame` that renders from this data, so the
        north-star scrape→render number still measures one full cycle.
        """
        # stamped at SCRAPE time: composed frames must report when the data
        # was pulled, not when a session re-rendered it (a selection toggle
        # near the end of a refresh interval must not present interval-old
        # metrics as current)
        stamp = _dt.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
        # tpulint: allow[wall-clock] scrape stamps are epoch timestamps
        stamp_ts = time.time()
        # The fetch runs OUTSIDE the publish lock (it can block for the
        # watchdog's whole lifetime) and ALL timer mutation happens inside
        # it — a stale compose served mid-stall must never see a
        # half-open timer frame (it would close a render-only frame and
        # skew the north-star percentiles).  Scrape time is measured
        # manually and recorded once the lock is held.
        t0 = time.perf_counter()
        try:
            samples = self.source.fetch()
        except Exception as e:  # noqa: BLE001 — error banner path catches all
            scrape_s = time.perf_counter() - t0
            with self._publish_lock:
                self.timer.start_frame()
                self.timer.current["scrape"] = scrape_s
                self.last_updated = stamp
                self.last_updated_ts = stamp_ts
                return self._publish_error(e)
        scrape_s = time.perf_counter() - t0
        # everything below mutates published state; the lock keeps a fetch
        # the watchdog parked (now completing on its own thread) from
        # swapping tables mid-compose
        with self._publish_lock:
            self.timer.start_frame()
            self._frame_open = True
            self.timer.current["scrape"] = scrape_s
            self.last_updated = stamp
            self.last_updated_ts = stamp_ts
            try:
                with self.timer.stage("normalize"):
                    df = to_wide(samples)
            except Exception as e:  # noqa: BLE001 — same banner path
                return self._publish_error(e)
            return self._publish_data(df)

    def _publish_error(self, e: Exception) -> None:
        """Error-cycle publication (reference banner path, app.py:225-227).
        Caller holds _publish_lock."""
        err = f"Error fetching TPU metrics: {e}"
        if err != self.last_error:  # log streaks once, not per cycle
            log.warning("%s", err)
        self.last_error = err
        if self.alert_engine is not None or self.anomaly_engine is not None:
            # a partial outage that turns total must keep the synthesized
            # (endpoint_down / overload) alerts current even though no
            # table was published; chip alerts from the last good frame
            # stay (their chips didn't recover — we just can't see them)
            from tpudash.alerts import SYNTHESIZED_RULES

            # tpulint: allow[wall-clock] alert "since" stamps are epochs
            now_w = time.time()
            synth = self._endpoint_alerts(now_w)
            synth += self._overload_alerts(now_w)
            synth += self._federation_alerts(now_w)
            synth += self._cold_alerts(now_w)
            synth = self._synth_dwell.apply(synth)
            # anomaly state freezes across an error cycle (no table to
            # evaluate) — the last computed entries keep serving
            synth = self._anomaly_alerts() + synth
            if synth or any(
                a.get("rule") in SYNTHESIZED_RULES for a in self.last_alerts
            ):
                from tpudash.alerts import sort_alerts

                kept = [
                    a
                    for a in self.last_alerts
                    if a.get("rule") not in SYNTHESIZED_RULES
                ]
                # fresh rollup first: a re-namespaced child alert from a
                # still-reachable child beats the stale kept copy
                self.last_alerts = self.silences.annotate(
                    sort_alerts(_merge_alerts(synth, kept)), now_w
                )
                self._notify_alert_transitions()
        # error cycles are timeline observations too: a total outage is
        # exactly when child flips / synthesized transitions matter most
        self.timeline.observe(
            # tpulint: allow[wall-clock] timeline events carry epoch stamps
            time.time(), self.last_alerts, self._federation_summary()
        )
        self._frame_open = False
        self.timer.end_frame()
        return None

    def _publish_data(self, df: "pd.DataFrame") -> "pd.DataFrame":
        """Success publication: table, identity caches, alerts, history.
        Caller holds _publish_lock."""
        if self.last_error is not None:
            log.info("metrics source recovered")
        self.last_error = None
        self.last_df = df
        # Identity columns extracted ONCE per refresh and shared by every
        # session's compose (arrow-backed string columns iterate per value
        # on .tolist()/.to_numpy() — at 256 chips doing this per compose
        # profiled at ~2 ms, and the chip-grid model is identical across
        # sessions except for the per-session "selected" flag).  The
        # columnar arena makes the steady state free: normalize reuses
        # the Index OBJECT while the population is unchanged, so one `is`
        # check proves every identity cache (and the compose-side caches
        # keyed on it) is still current.
        if df.index is self._ident_index and self._chips_base:
            keys = self._keys_list
        else:
            keys = df.index.tolist()
            chip_id_list = df["chip_id"].tolist()
            slice_list = df["slice_id"].tolist()
            host_list = df["host"].tolist()
            accel_list = (
                df[schema.ACCEL_TYPE].fillna("").tolist()
                if schema.ACCEL_TYPE in df
                else [""] * len(df)
            )
            self._ident_chips = np.asarray(chip_id_list, dtype=np.int64)
            self._ident_slices = np.asarray(slice_list, dtype=object)
            self._ident_keys = np.asarray(keys, dtype=object)
            self._ident_accels = accel_list
            self._chips_base = [
                {
                    "key": k,
                    "chip_id": int(c),
                    "slice": s,
                    "host": h,
                    "model": _model_name(a),
                }
                for k, c, s, h, a in zip(
                    keys, chip_id_list, slice_list, host_list, accel_list
                )
            ]
            self._ident_index = df.index
            self._keys_list = keys
            # population changed: every population-keyed compose cache
            # (chips grid, group codes, heatmap geometry) is stale
            self._chips_sel_cache = None
            self._group_cache = None
            self._heatmap_geo = None
        self.available = keys
        # dense extraction + outlier analysis run BEFORE the alert stage
        # now: the anomaly engine consumes the straggler detector's
        # firing entries and its entries join the synthesized set below
        arr, cols = self._df_block = dense_block(df)
        if self.straggler_detector is not None:
            with self.timer.stage("analyze"):
                self.last_stragglers = self.straggler_detector.evaluate(
                    df, block=self._df_block
                )
        # tpulint: allow[wall-clock] alert/anomaly epoch stamps
        now_w = time.time()
        if self.anomaly_engine is not None:
            with self.timer.stage("anomaly"):
                self.last_anomalies = self.anomaly_engine.observe(
                    now_w,
                    df,
                    block=self._df_block,
                    # None (not []) when the detector is off — the
                    # honest "no detector ran" signal (the fabric scan
                    # itself is screen-gated either way)
                    stragglers=(
                        self.last_stragglers
                        if self.straggler_detector is not None
                        else None
                    ),
                    keys=keys,
                )
        # the alert plane exists when EITHER engine is on: with
        # TPUDASH_ALERT_RULES=off the anomaly entries (and the
        # synthesized service rules) must still page/surface — the
        # replay twin merges them unconditionally and live must agree
        if self.alert_engine is not None or self.anomaly_engine is not None:
            with self.timer.stage("alerts"):
                from tpudash.alerts import sort_alerts

                alerts = (
                    self.alert_engine.evaluate(df)
                    if self.alert_engine is not None
                    else []
                )
                synth = self._endpoint_alerts(now_w)
                synth += self._overload_alerts(now_w)
                synth += self._federation_alerts(now_w)
                synth += self._cold_alerts(now_w)
                synth = self._synth_dwell.apply(synth)
                # anomaly entries carry their OWN dwell (the engine
                # applies TPUDASH_ANOMALY_DWELL) — joined after the
                # service-side dwell so holds never double-apply
                synth = self._anomaly_alerts() + synth
                self.last_alerts = self.silences.annotate(
                    sort_alerts(_merge_alerts(alerts, synth)), now_w
                )
            self._notify_alert_transitions()
        # every publish is a timeline observation: alert transitions and
        # federation child flips become incident events (/api/incidents)
        self.timeline.observe(
            now_w, self.last_alerts, self._federation_summary()
        )
        # Fleet-wide trend history, one point per refresh interval (burst
        # renders from selection POSTs must not pollute the cadence).
        # Averages cover ALL chips in scope — per-browser selections are
        # session-local now and must not steer the shared sparklines; this
        # also matches the backfill scope (_backfill_history).
        # ring points are persisted epoch timestamps; the cadence gate
        # compares against restored wall stamps.
        # tpulint: allow[wall-clock] trend ring carries epoch timestamps
        now = time.time()
        if (
            not self.history
            or now - self.history[-1][0] >= self.cfg.refresh_interval
        ):
            if arr is not None:
                col_pos = {c: i for i, c in enumerate(cols)}
                avgs = {
                    p.column: block_average(arr, col_pos[p.column], p.column)
                    for p in self._active_panels(df)
                    if p.column in col_pos
                }
            else:
                avgs = {
                    p.column: column_average(df, p.column)
                    for p in self._active_panels(df)
                }
            self.history.append((now, avgs))
            # per-chip ring (drill-down trends), same cadence
            if arr is not None:
                if (
                    keys != self._chip_hist_keys
                    or cols != self._chip_hist_cols
                ):
                    if keys == self._chip_hist_keys and self.chip_history:
                        # same chips, different metric set (a live scrape
                        # is richer than the Prometheus backfill): project
                        # stored points onto the new columns instead of
                        # throwing the history away
                        old_pos = {
                            c: i for i, c in enumerate(self._chip_hist_cols)
                        }
                        proj = [old_pos.get(c, -1) for c in cols]
                        realigned = deque(maxlen=self.chip_history.maxlen)
                        for ts_old, m in self.chip_history:
                            nm = np.full(
                                (m.shape[0], len(cols)),
                                np.nan,
                                dtype=np.float32,
                            )
                            for j, src in enumerate(proj):
                                if src >= 0:
                                    nm[:, j] = m[:, src]
                            realigned.append((ts_old, nm))
                        self.chip_history = realigned
                    else:
                        self.chip_history.clear()
                    self._chip_hist_keys = keys
                    self._chip_hist_cols = cols
                    self._chip_hist_rowmap = {
                        k: i for i, k in enumerate(keys)
                    }
                self.chip_history.append((now, arr.astype(np.float32)))
            # the same cadence-gated frame mirrors into the compressed
            # long-horizon store (per-chip rows + the fleet pseudo-row);
            # head appends are pointer work, sealing runs on its thread
            self._tsdb_ingest(now, keys, cols, arr, avgs)
        # periodic trend persistence, OFF the frame path (compression of
        # a full 256-chip ring takes ~100 ms).  Monotonic cadence: the
        # ring timestamps above are wall-clock (persisted, compared to
        # restored points), but WHEN to save is pure interval arithmetic
        now_m = time.monotonic()
        if (
            self.cfg.history_path
            and now_m - self._last_history_save >= self.cfg.history_save_interval
        ):
            self._last_history_save = now_m
            threading.Thread(target=self.save_history, daemon=True).start()
        return df

    def compose_frame(self, state: "SelectionState | None" = None) -> dict:
        """Selection-dependent frame assembly under the publish lock — a
        fetch the watchdog parked must not swap tables mid-compose."""
        with self._publish_lock:
            return self._compose_frame_locked(state)

    def _compose_frame_locked(
        self, state: "SelectionState | None" = None
    ) -> dict:
        """Selection-dependent frame assembly for ONE viewer session over
        the table :meth:`refresh_data` last pulled — the render half of the
        reference's loop (app.py:320-486), cheap enough to run per session.
        ``state`` defaults to the anonymous/global session."""
        state = state if state is not None else self.state
        frame: dict = {
            "last_updated": self.last_updated,
            "refresh_interval": self.cfg.refresh_interval,
            "use_gauge": state.use_gauge,
            "error": self.last_error,
            "source_health": self.source_health(),
        }
        fs = self._federation_summary()
        if fs:
            # the fleet pane's truth channel: per-child staleness_s /
            # breaker state / status, and the partial marker — present on
            # ERROR frames too (an all-dark fleet must still say which
            # children went dark, not just show a banner)
            frame["federation"] = fs
            if fs["partial"]:
                frame["partial"] = True
        df = self.last_df
        if df is None and self.refresh_stalled and frame["error"] is None:
            # the very first fetch is stalled: nothing to serve yet, and
            # the page must say why instead of rendering an empty shell
            frame["error"] = self.refresh_stalled
        if frame["error"] is not None or df is None:
            frame["chips"] = []
            frame["timings"] = self.timer.summary()
            return frame
        if self.alert_engine is not None or self.anomaly_engine is not None:
            frame["alerts"] = self.last_alerts
        if self.straggler_detector is not None:
            frame["stragglers"] = self.last_stragglers
        if self.anomaly_engine is not None:
            frame["anomalies"] = self.last_anomalies
        # partial degradation (MultiSource): healthy slices render, failed
        # endpoints surface as warnings instead of blanking the page
        partial = getattr(self.source, "last_errors", None)
        warnings = (
            [f"endpoint {name}: {err}" for name, err in partial.items()]
            if partial
            else []
        )
        if self.refresh_stalled:
            warnings.append(self.refresh_stalled)
        if fs and fs["partial"]:
            k = fs["children_total"] - fs["children_live"]
            warnings.append(
                f"fleet view partial: {k}/{fs['children_total']} federated "
                "children degraded — their panels show last-good data "
                "(see the federation block for per-child staleness)"
            )
        if warnings:
            frame["warnings"] = warnings
        # only the FIRST compose after a refresh lands in the timer frame:
        # further sessions' composes must not append render-only entries
        # that would skew the scrape→render percentiles
        render_timing = (
            self.timer.stage("render")
            if self._frame_open
            else contextlib.nullcontext()
        )
        with render_timing:
            available = self.available
            selected = state.sync(available)
            sel_df = filter_selected(df, selected)
            panels = self._active_panels(df)
            use_gauge = state.use_gauge

            # chips grid with per-session selection flags: population- and
            # selection-keyed cache (population invalidates via publish;
            # bounded by cohort diversity).  The cached list is shared
            # across frames — consumers treat frames as immutable.
            sel_t = tuple(selected)
            cached = self._chips_sel_cache
            if cached is not None and cached[0] == sel_t:
                frame["chips"] = cached[1]
            else:
                sel_set = set(selected)
                chips_sel = [
                    dict(c, selected=c["key"] in sel_set)
                    for c in self._chips_base
                ]
                self._chips_sel_cache = (sel_t, chips_sel)
                frame["chips"] = chips_sel
            # copy: the cached frame must not alias the live selection list
            frame["selected"] = list(selected)
            frame["panel_specs"] = [
                {"column": p.column, "title": p.title, "unit": p.unit}
                for p in panels
            ]
            # capability honesty: a reference-parity panel (util/HBM/temp/
            # power, app.py:352-409) the source cannot feed is declared
            # with a reason, never silently dropped
            frame["unavailable_panels"] = [
                {
                    "column": s.column,
                    "title": s.title,
                    "reason": PANEL_GAP_REASONS.get(s.column, _GENERIC_GAP),
                }
                for s in schema.PANELS
                if s.column not in df.columns
            ]

            if not sel_df.empty:
                # ONE numeric-matrix extraction shared by averages, stats,
                # breakdowns, and heatmap values — each pandas column-subset
                # copy profiled at ~3 ms/frame at 256 chips.  The select-all
                # fast path reuses the block refresh_data already extracted.
                if (
                    sel_df is df
                    and self._df_block[0] is not None
                    and self._df_block[0].shape[0] == len(df)
                ):
                    block = self._df_block
                else:
                    block = dense_block(sel_df)
                arr, cols = block
                col_pos = {c: i for i, c in enumerate(cols)}
                if arr is not None:
                    avgs = {
                        spec.column: block_average(
                            arr, col_pos[spec.column], spec.column
                        )
                        for spec in panels
                        if spec.column in col_pos
                    }
                else:  # legacy mixed-dtype frames
                    avgs = {
                        spec.column: column_average(sel_df, spec.column)
                        for spec in panels
                    }
                frame["average"] = self._average_row(
                    sel_df, panels, use_gauge, avgs
                )
                frame["trends"] = self._trends(sel_df, panels)
                if len(sel_df) <= self.cfg.per_chip_panel_limit:
                    frame["device_rows"] = self._device_rows(sel_df, panels, use_gauge)
                    frame["heatmaps"] = []
                else:
                    frame["device_rows"] = []
                    frame["heatmaps"] = self._heatmaps(
                        sel_df, df, panels, block=block
                    )
                stats = compute_stats(sel_df, block=block)
                # display rounding parity (app.py:480-481)
                frame["stats"] = {
                    m: {k: round(v, 2) for k, v in s.items()}
                    for m, s in stats.items()
                }
                frame["breakdown"] = self._breakdown(sel_df, panels, block=block)
            else:
                frame["average"] = None
                frame["device_rows"] = []
                frame["heatmaps"] = []
                frame["trends"] = []
                frame["stats"] = {}
                frame["breakdown"] = {}

        if self._frame_open:
            self._frame_open = False
            self.timer.end_frame()
        frame["timings"] = self.timer.summary()
        return frame

    def render_frame(self, state: "SelectionState | None" = None) -> dict:
        """One full cycle — refresh + compose — for a single session (the
        reference's single-viewer loop; bench.py and the CLI use this)."""
        self.refresh_data()
        return self.compose_frame(state)
