"""Per-browser-session UI state — the reference's ``st.session_state``.

The reference scopes ``selected_gpus`` / ``use_gauge`` / ``last_selection``
to one browser session (reference app.py:252-260): two people watching the
same dashboard never fight over each other's checkboxes or gauge style.
tpudash's aiohttp shell restores those semantics with a cookie-identified,
bounded, TTL-evicted server-side map of :class:`SelectionState`.

The pre-existing global state remains as the **anonymous default**: requests
without a session cookie (curl, API consumers, k8s probes) see exactly the
old single-state behavior, and only the default state participates in
``TPUDASH_STATE_PATH`` persistence — per-browser sessions are ephemeral,
like the reference's (a browser restart resets them, SURVEY.md §5
checkpoint/resume note).

Each entry also carries the per-session composed-frame cache keyed by
``(data_version, state_version)`` for the POLLING transport: the expensive
scrape/normalize runs once per refresh interval for ALL sessions (the
shared half lives in ``DashboardService.refresh_data``), while the cheap
per-selection compose is cached per session so many tabs of one browser
still cost one render.  The SSE transport no longer caches anything here:
sessions sharing a (selection, style) state compose through one *cohort*
(tpudash.broadcast.cohort), whose sealed buffers are shared by every
subscriber — and by every worker process in ``TPUDASH_WORKERS`` mode.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from tpudash.app.state import SelectionState, _sort_key


class SessionEntry:
    """One viewer session: its selection state plus the polling
    transport's composed-frame cache (the SSE transport serves shared
    cohort seals instead — nothing per-session to retain)."""

    __slots__ = (
        "state",
        "state_version",
        "frame",
        "frame_key",
        "last_seen",
    )

    def __init__(self, state: SelectionState):
        self.state = state
        #: bumped by the server on every mutation (select/style POSTs);
        #: part of the compose-cache key
        self.state_version = 0
        self.frame: "dict | None" = None
        self.frame_key: "tuple | None" = None
        self.last_seen = 0.0


class SessionStore:
    """Bounded, TTL-evicted map of session id → :class:`SessionEntry`.

    ``entry(None)`` / ``entry("")`` returns the default (anonymous) entry,
    which is never evicted.  Unknown ids lazily create fresh sessions (a
    stale cookie after a server restart simply becomes a new session — the
    reference's browser-refresh-resets-state behavior).  Access refreshes
    recency; eviction removes TTL-expired entries first (they are exactly
    the least-recently-used ones) and then trims to the size bound.
    """

    def __init__(
        self,
        default_state: SelectionState,
        limit: int = 256,
        ttl: float = 1800.0,
        clock=time.monotonic,
    ):
        self.default = SessionEntry(default_state)
        self.limit = max(1, int(limit))
        self.ttl = float(ttl)
        self._clock = clock
        self._entries: "OrderedDict[str, SessionEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def entry(self, sid: "str | None") -> SessionEntry:
        # TTL-sweep on EVERY access, not just inserts: each retained entry
        # pins a cached full-figure payload, so expired sessions must not
        # linger until the next brand-new visitor happens to arrive
        now = self._clock()
        self._evict(now)
        if not sid:
            return self.default
        e = self._entries.get(sid)
        if e is None:
            # size bound applies only when inserting — never evict a live
            # LRU entry just because an existing session was accessed
            while len(self._entries) >= self.limit:
                self._entries.popitem(last=False)
            e = self._entries[sid] = SessionEntry(SelectionState())
        else:
            self._entries.move_to_end(sid)
        e.last_seen = now
        return e

    def peek(self, sid: "str | None") -> "SessionEntry | None":
        """Read-only lookup: the entry if it exists, else None — no
        creation, no recency touch, no TTL sweep.  For observers that
        must not perturb the store (tests asserting an evicted stream's
        entry survived eviction).  Note the shed path's swarm-safety
        does NOT come from here: ``_shed_response`` never touches the
        store at all."""
        if not sid:
            return self.default
        return self._entries.get(sid)

    def invalidate_all(self) -> None:
        """Bump every session's state version — global state (e.g. alert
        silences) changed, so every cached compose is stale."""
        self.default.state_version += 1
        for e in self._entries.values():
            e.state_version += 1

    # -- persistence (rides the TPUDASH_STATE_PATH checkpoint) ---------------
    def to_dicts(self) -> dict:
        """sid → persisted UI state + idle age.  ``last_seen`` uses a
        monotonic clock that does not survive restarts, so the AGE is
        persisted and re-anchored on restore — TTL eviction continues
        across the restart instead of resetting."""
        now = self._clock()
        return {
            sid: dict(e.state.to_dict(), idle_s=round(now - e.last_seen, 1))
            for sid, e in self._entries.items()
        }

    def restore(self, data: dict) -> int:
        """Recreate sessions from a checkpoint section (bounded by the
        store's own limit, already-TTL-expired entries skipped, corrupt
        entries ignored).  Returns the number restored."""
        if not isinstance(data, dict) or self.limit <= 0:
            # limit=0 must restore nothing: items[-0:] is the WHOLE list
            return 0
        now = self._clock()
        restored = 0

        def _idle(entry: dict) -> float:
            # a corrupt idle_s must skew ONE entry, not crash restore
            # (and thereby server startup) — treat it as ancient
            try:
                return float(entry.get("idle_s", 0.0))
            except (TypeError, ValueError):
                return float("inf")

        # most-recently-seen last, so LRU trimming keeps the freshest
        items = sorted(
            (
                (sid, e)
                for sid, e in data.items()
                if isinstance(e, dict)
            ),
            key=lambda kv: -_idle(kv[1]),
        )
        for sid, item in items[-self.limit:]:
            try:
                idle = _idle(item)
                if idle >= self.ttl:
                    continue
                state = SelectionState()
                state.selected = sorted(
                    (str(k) for k in item.get("selected", [])),
                    key=_sort_key,
                )
                state.use_gauge = bool(item.get("use_gauge", True))
                state.last_selection = [
                    str(k) for k in item.get("last_selection", [])
                ]
                state._initialized = True
                e = self._entries[str(sid)] = SessionEntry(state)
                e.last_seen = now - idle
                restored += 1
            except (TypeError, ValueError):
                continue
        return restored

    def _evict(self, now: float) -> None:
        # LRU order == insertion-after-move_to_end order, so TTL-expired
        # entries cluster at the front; stop at the first live one
        while self._entries:
            sid, e = next(iter(self._entries.items()))
            if now - e.last_seen >= self.ttl:
                del self._entries[sid]
            else:
                break
