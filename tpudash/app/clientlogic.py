"""The page's client-side logic, written ONCE in Python.

These functions run in two places: executed directly by the test suite
(the delta fuzz corpus asserts ``apply_delta(prev, delta)`` here is
byte-identical to the server reference ``tpudash/app/delta.py``), and
transpiled to JavaScript by ``tpudash/app/pyjs.py`` into the served page
(``html.py`` embeds the generated block; a parity test pins it).  That
removes the hand-maintained JS mirror that nobody could test in this
image (VERDICT r3 weak #1) — drift between the page and the transport
contract is now structurally impossible.

Rules of the house (enforced by the transpiler): only constructs whose
semantics are identical over JSON data in both languages — no bare
truthiness, no ``zip``, no comprehensions, explicit counted loops.
Mutation is in place (the JS side patches the live frame object); the
Python tests deep-copy before calling.

Reference contract: tpudash/app/delta.py (apply_delta, SCALAR_FIELDS);
reference UI behavior: the reference resets all state per refresh
(app.py:252-260) — the reconnect plan here instead degrades SSE→polling
and recovers, pinned by test_client_parity.
"""

from __future__ import annotations


def patch_fig(figure, p):
    """Write one gauge/bar value+color patch into a figure dict —
    mirror of delta.apply_delta's patch_fig."""
    t = figure["data"][0]
    if t["type"] == "indicator":
        t["value"] = p["value"]
        t["gauge"]["bar"]["color"] = p["color"]
    else:
        t["x"] = [p["value"]]
        t["marker"]["color"] = p["color"]


def apply_delta(f, d):
    """Patch a value-only SSE delta into the last full frame, in place.
    Must match tpudash/app/delta.py::apply_delta byte-for-byte on JSON
    data; the scalar-field list below must equal delta.SCALAR_FIELDS
    (pinned by test_client_parity)."""
    for k in [
        "last_updated",
        "timings",
        "source_health",
        "alerts",
        "stragglers",
        "warnings",
        "stats",
        "breakdown",
        "unavailable_panels",
    ]:
        if k in d:
            f[k] = d[k]
        else:
            if k in f:
                del f[k]
    if "average" in d:
        figs = f["average"]["figures"]
        patches = d["average"]
        for i in range(len(patches)):
            patch_fig(figs[i]["figure"], patches[i])
    if "device_rows" in d:
        rows = f["device_rows"]
        row_patches = d["device_rows"]
        for i in range(len(row_patches)):
            figs = rows[i]["figures"]
            patches = row_patches[i]
            for j in range(len(patches)):
                patch_fig(figs[j]["figure"], patches[j])
    if "heatmaps" in d:
        maps = f["heatmaps"]
        zs = d["heatmaps"]
        for i in range(len(zs)):
            maps[i]["figure"]["data"][0]["z"] = zs[i]
    if "trends" in d:
        trends = f["trends"]
        patches = d["trends"]
        for i in range(len(patches)):
            t = trends[i]["figure"]["data"][0]
            t["x"] = patches[i]["x"]
            t["y"] = patches[i]["y"]
            t["line"]["color"] = patches[i]["color"]
    return f


def stream_event_plan(kind, has_last_frame):
    """What to do with one SSE message: "delta" patches the last frame,
    "full" replaces it, "refetch" means a delta arrived before any full
    frame (missed the first event) and the client must GET /api/frame."""
    if kind == "delta":
        if has_last_frame == True:  # noqa: E712 — transpiled comparison
            return "delta"
        return "refetch"
    return "full"


def stream_error_plan(is_closed, has_timer):
    """Recovery plan for an SSE error: always fall back to polling
    (unless a poll timer already runs); re-open the stream only for a
    CLOSED EventSource — transient errors auto-reconnect on their own,
    a closed one (proxy returned non-200) never retries itself."""
    plan = {"poll_ms": 0, "reopen_ms": 0}
    if has_timer == False:  # noqa: E712 — transpiled comparison
        plan["poll_ms"] = 5000
    if is_closed == True:  # noqa: E712 — transpiled comparison
        plan["reopen_ms"] = 15000
    return plan


# --- fallback-renderer decision logic ---------------------------------------
# The no-plotly renderer (html.py) draws the same figure dicts as HTML /
# SVG.  Its DOM assembly stays in JS, but every *decision* — band
# placement, color selection, cell classification, sparkline scaling —
# lives here so the air-gapped rendering path is test-covered too.


def clamp_frac(v, vmax):
    """v/vmax clamped into [0, 1]; 0 when vmax is not positive."""
    if vmax > 0:
        f = v / vmax
        if f < 0:
            return 0
        if f > 1:
            return 1
        return f
    return 0


def color_from_scale(scale, frac):
    """Plotly-style colorscale [[stop, color], ...] → the color of the
    last stop at-or-below frac (stops ascend; frac pre-clamped)."""
    c = scale[0][1]
    for i in range(len(scale)):
        if frac >= scale[i][0]:
            c = scale[i][1]
    return c


def meter_geometry(value, max_val, steps):
    """Gauge/bar meter layout: fill percent plus one {left, width,
    color} percent-box per threshold band."""
    g = {"pct": clamp_frac(value, max_val) * 100, "bands": []}
    for i in range(len(steps)):
        s = steps[i]
        if max_val > 0:
            g["bands"].append(
                {
                    "left": s["range"][0] / max_val * 100,
                    "width": (s["range"][1] - s["range"][0]) / max_val * 100,
                    "color": s["color"],
                }
            )
    return g


def heat_cell(v, key, zmax, scale):
    """Classify one heatmap cell: a missing value with a chip key is a
    DESELECTED chip (clickable, re-selects), without a key it's torus
    padding; otherwise pick the value's colorscale color."""
    if v is None:
        if key is None:
            return {"kind": "blank"}
        return {"kind": "deselected"}
    return {
        "kind": "cell",
        "color": color_from_scale(scale, clamp_frac(v, zmax)),
    }


def heat_cells(plan):
    """Row-major cell models for the fallback heatmap: the customdata
    alignment guards and value-vs-key classification (via heat_cell) all
    happen here, so a renderer bug can't silently mis-key a cell.  The
    flat list wraps into rows by the CSS grid's ``plan["cols"]``."""
    out = []
    z = plan["z"]
    cd = plan["customdata"]
    for y in range(len(z)):
        row = z[y]
        for x in range(len(row)):
            key = None
            if cd is not None:
                if y < len(cd):
                    if cd[y] is not None:
                        if x < len(cd[y]):
                            if cd[y][x] is not None:
                                if cd[y][x] != "":
                                    key = cd[y][x]
            cell = heat_cell(row[x], key, plan["zmax"], plan["colorscale"])
            cell["key"] = key
            cell["v"] = row[x]
            out.append(cell)
    return out


def spark_points(ys, ymax, w, h):
    """Sparkline polyline points in a w×h viewBox: x spreads evenly,
    y scales by ymax (clamped), origin at the top like SVG."""
    pts = []
    n = len(ys)
    for i in range(n):
        if n > 1:
            x = i / (n - 1) * w
        else:
            x = 0
        pts.append([x, h - clamp_frac(ys[i], ymax) * h])
    return pts


def _is_js_array_index(k: str) -> bool:
    """Canonical JS array index: ASCII digits only, no leading zeros,
    < 2^32-1.  The ASCII guard matters: str.isdigit() accepts Unicode
    digits ("²", Arabic-Indic numerals) that a JS engine treats as plain
    string keys — and int() even rejects some of them."""
    if not (k.isascii() and k.isdigit()):
        return False
    n = int(k)
    return str(n) == k and n < 4294967295


def keys(d):
    """Dict keys in REAL JS ``Object.keys`` order.  NOT transpiled: the
    transpiler maps calls to ``keys(x)`` directly onto ``Object.keys(x)``
    (pyjs), so this Python body must replicate the engine's
    OrdinaryOwnPropertyKeys ordering — integer-like keys ascend
    numerically first, then the remaining keys in insertion order.  A
    plain ``list(d.keys())`` would silently diverge in browsers for maps
    keyed by numeric strings (hosts/slices named "2", "10")."""
    numeric = sorted(
        (k for k in d.keys() if _is_js_array_index(k)), key=int
    )
    rest = [k for k in d.keys() if not _is_js_array_index(k)]
    return numeric + rest


# --- renderer dispatch (VERDICT r4 #4: was hand-written renderFigure) --------


def figure_title(fig):
    """The reference's title chain ((trace.title && .text) || (layout
    .title && .text) || '') including its ||-falsiness on empty text."""
    t = fig["data"][0]
    out = ""
    if "title" in t:
        if t["title"] is not None:
            if "text" in t["title"]:
                if t["title"]["text"] is not None and t["title"]["text"] != "":
                    out = t["title"]["text"]
    if out == "":
        lay = fig["layout"]
        if "title" in lay:
            if lay["title"] is not None:
                if "text" in lay["title"]:
                    if (
                        lay["title"]["text"] is not None
                        and lay["title"]["text"] != ""
                    ):
                        out = lay["title"]["text"]
    return out


def bar_band_steps(layout):
    """A bar figure's translucent band rects (layout.shapes) in the
    {range, color} shape meter_geometry expects."""
    steps = []
    if "shapes" in layout:
        if layout["shapes"] is not None:
            for s in layout["shapes"]:
                steps.append(
                    {"range": [s["x0"], s["x1"]], "color": s["fillcolor"]}
                )
    return steps


def figure_render_plan(fig):
    """Fallback-renderer dispatch for one figure dict: which renderer
    (meter / heat / spark / none) and every parameter pre-extracted, so
    the hand JS only assembles DOM around a fully-decided plan."""
    t = fig["data"][0]
    title = figure_title(fig)
    if t["type"] == "indicator":
        # every gauge sub-field is optional on the wire: a figure built
        # without steps / axis range / bar color must take the SAME
        # guarded path here and in the generated JS (missing-key access
        # raises KeyError in Python but yields undefined in JS — an
        # explicit `in` check is the only shape both sides agree on)
        mx = 100
        steps = []
        color = None
        if "gauge" in t and t["gauge"] is not None:
            g = t["gauge"]
            if "axis" in g and g["axis"] is not None:
                if "range" in g["axis"]:
                    r = g["axis"]["range"]
                    if r is not None and len(r) > 1:
                        mx = r[1]
            if "steps" in g:
                if g["steps"] is not None:
                    steps = g["steps"]
            if "bar" in g and g["bar"] is not None:
                if "color" in g["bar"]:
                    color = g["bar"]["color"]
        return {
            "kind": "meter",
            "title": title,
            "value": t["value"],
            "max": mx,
            "steps": steps,
            "color": color,
        }
    if t["type"] == "bar":
        return {
            "kind": "meter",
            "title": title,
            "value": t["x"][0],
            "max": fig["layout"]["xaxis"]["range"][1],
            "steps": bar_band_steps(fig["layout"]),
            "color": t["marker"]["color"],
        }
    if t["type"] == "heatmap":
        zmax = 100
        if "zmax" in t:
            if t["zmax"] is not None and t["zmax"] != 0:
                zmax = t["zmax"]
        cols = 0
        if len(t["z"]) > 0:
            cols = len(t["z"][0])
        cd = None
        if "customdata" in t:
            cd = t["customdata"]
        cs = None
        if "colorscale" in t:
            cs = t["colorscale"]
        return {
            "kind": "heat",
            "title": title,
            "z": t["z"],
            "zmax": zmax,
            "cols": cols,
            "customdata": cd,
            "colorscale": cs,
        }
    if t["type"] == "scatter":
        ys = t["y"]
        ymax = None
        lay = fig["layout"]
        if "yaxis" in lay and lay["yaxis"] is not None:
            if "range" in lay["yaxis"]:
                yr = lay["yaxis"]["range"]
                if yr is not None and len(yr) > 1:
                    ymax = yr[1]
        if ymax is None or ymax == 0:
            ymax = 1
            for i in range(len(ys)):
                if ys[i] > ymax:
                    ymax = ys[i]
        last = None
        if len(ys) > 0:
            last = ys[len(ys) - 1]
        color = None
        if "line" in t and t["line"] is not None:
            if "color" in t["line"]:
                color = t["line"]["color"]
        return {
            "kind": "spark",
            "title": title,
            "ys": ys,
            "ymax": ymax,
            "color": color,
            "last": last,
        }
    return {"kind": "none"}


# --- drill-down decisions (open/close/response handling) ---------------------


def drill_response_plan(request_key, current_key, status, fetch_failed):
    """What to do with a drill-down fetch outcome: drop stale responses
    (user closed or moved on mid-flight), close on 404 (chip left the
    fleet), keep the last detail on transient errors, render otherwise."""
    if fetch_failed == True:  # noqa: E712 — transpiled comparison
        return "keep"
    if current_key is None or current_key != request_key:
        return "drop"
    if status == 404:
        return "close"
    if status < 200 or status >= 300:
        return "keep"
    return "render"


def firing_entries(entries):
    """The firing subset of an alert/straggler list (drill-down rows)."""
    out = []
    if entries is not None:
        for e in entries:
            if e["state"] == "firing":
                out.append(e)
    return out


def alert_is_silenced(a):
    """True only for an explicit silenced=true flag — a missing field is
    not an acknowledgement (shared by the banner and drill models)."""
    if "silenced" in a:
        if a["silenced"] == True:  # noqa: E712 — transpiled comparison
            return True
    return False


def drill_view_model(d):
    """Drill-down view model: every per-row decision the panel makes —
    firing filters, the acknowledge-button label, missing-measurement
    and missing-neighbor placeholders, the cold-link flag — decided
    here; the hand JS only prints fields."""
    alerts = []
    raw_alerts = None
    if "alerts" in d:
        raw_alerts = d["alerts"]
    firing = firing_entries(raw_alerts)
    for a in firing:
        sil = alert_is_silenced(a)
        label = "silence 1h"
        if sil == True:  # noqa: E712 — transpiled comparison
            label = "unsilence"
        alerts.append(
            {
                "rule": a["rule"],
                "chip": a["chip"],
                "value": a["value"],
                "silenced": sil,
                "button_label": label,
            }
        )
    raw_stragglers = None
    if "stragglers" in d:
        raw_stragglers = d["stragglers"]
    lagging = firing_entries(raw_stragglers)
    links = []
    if "links" in d:
        if d["links"] is not None:
            for link in d["links"]:
                cold = False
                if "straggler" in link:
                    if link["straggler"] == True:  # noqa: E712
                        cold = True
                gbps = None
                if "gbps" in link:
                    gbps = link["gbps"]
                neighbor = None
                if "neighbor" in link:
                    if link["neighbor"] is not None:
                        if link["neighbor"] != "":
                            neighbor = link["neighbor"]
                links.append(
                    {
                        "dir": link["dir"],
                        "cold": cold,
                        "gbps": gbps,
                        "neighbor": neighbor,
                    }
                )
    neighbors = []
    if "neighbors" in d:
        if d["neighbors"] is not None:
            neighbors = d["neighbors"]
    return {
        "alerts": alerts,
        "show_alerts": len(alerts) > 0,
        "stragglers": lagging,
        "show_stragglers": len(lagging) > 0,
        "links": links,
        "show_links": len(links) > 0,
        "neighbors": neighbors,
        "show_neighbors": len(neighbors) > 0,
    }


def silence_toggle_request(rule, chip, silenced):
    """The acknowledge-button contract: silenced alerts unsilence,
    firing ones get a 1h silence scoped to (rule, chip)."""
    if silenced == True:  # noqa: E712 — transpiled comparison
        return {
            "path": "/api/alerts/unsilence",
            "body": {"rule": rule, "chip": chip},
        }
    return {
        "path": "/api/alerts/silence",
        "body": {"rule": rule, "chip": chip, "ttl_s": 3600},
    }


# --- replay scrub mapping ----------------------------------------------------


def replay_seek_request(index):
    """Slider position → seek body: an explicit scrub always pauses, so
    the frame the operator chose holds instead of auto-advancing."""
    return {"index": index, "paused": True}


def replay_toggle_request(paused):
    return {"paused": not paused == True}  # noqa: E712


def replay_bar_model(pos, slider_active):
    """Scrub-bar view model from /api/replay position JSON.  ``pos``
    (1-based) is None before the first snapshot renders; the slider is
    never yanked while the operator is dragging it (slider_active)."""
    m = {
        "max": pos["total"] - 1,
        "set_value": None,
        "paused": pos["paused"] == True,  # noqa: E712
        "pos": None,
        "total": pos["total"],
        "ts": None,
    }
    if pos["index"] is not None:
        m["pos"] = pos["index"] + 1
        if slider_active == False:  # noqa: E712 — transpiled comparison
            m["set_value"] = pos["index"]
    if "ts" in pos:
        if pos["ts"] is not None:
            m["ts"] = pos["ts"]
    return m


# --- table / banner view models (VERDICT r4 #4) ------------------------------


def stats_table_model(stats):
    """Statistics table: mean/max/min = reference parity, p50/p95 =
    fleet-scale additions — a column appears only when the first metric
    carries it (probe sources skip percentiles)."""
    metrics = keys(stats)
    if len(metrics) == 0:
        return {"metrics": [], "cols": [], "rows": []}
    first = stats[metrics[0]]
    cols = []
    for k in ["mean", "p50", "p95", "max", "min"]:
        if k in first:
            cols.append(k)
    rows = []
    for i in range(len(metrics)):
        s = stats[metrics[i]]
        row = []
        for j in range(len(cols)):
            if cols[j] in s:
                row.append(s[cols[j]])
            else:
                row.append(None)
        rows.append(row)
    return {"metrics": metrics, "cols": cols, "rows": rows}


def breakdown_table_model(bd, panel_specs):
    """Per-slice/per-host tables: one per dimension, a panel column
    included only when some row actually carries it."""
    tables = []
    if bd is None:
        return tables
    dims = keys(bd)
    for di in range(len(dims)):
        dim = dims[di]
        rows = bd[dim]
        row_keys = keys(rows)
        cols = []
        if panel_specs is not None:
            for p in panel_specs:
                found = False
                for i in range(len(row_keys)):
                    if p["column"] in rows[row_keys[i]]:
                        found = True
                if found == True:  # noqa: E712 — transpiled comparison
                    cols.append(p)
        title = dim
        if dim == "by_slice":
            title = "Per-slice averages"
        if dim == "by_host":
            title = "Per-host averages"
        head = "slice"
        if dim == "by_host":
            head = "host"
        body = []
        for i in range(len(row_keys)):
            k = row_keys[i]
            cells = [k, rows[k]["chips"]]
            for j in range(len(cols)):
                if cols[j]["column"] in rows[k]:
                    cells.append(rows[k][cols[j]["column"]])
                else:
                    cells.append(None)
            body.append(cells)
        tables.append({"title": title, "head": head, "cols": cols, "rows": body})
    return tables


def chip_grid_model(chips):
    """Checkbox-grid model: per-slice key groups (slice bar shows only
    on multi-slice fleets) and the selected count."""
    entries = []
    index = {}
    selected = 0
    for c in chips:
        # prefixed lookup key: a slice literally named "__proto__" would
        # otherwise hit the JS prototype setter on assignment and never
        # become an own property (membership itself is own-property-safe
        # via the transpiler's hasOwnProperty mapping)
        slot = "s:" + c["slice"]
        if slot not in index:
            index[slot] = len(entries)
            entries.append({"slice": c["slice"], "keys": []})
        entries[index[slot]]["keys"].append(c["key"])
        if c["selected"] == True:  # noqa: E712 — transpiled comparison
            selected = selected + 1
    return {
        "slices": entries,
        "show_bar": len(entries) > 1,
        "selected": selected,
        "total": len(chips),
    }


def alert_banner_model(alerts):
    """Alert banner: silenced (acknowledged) alerts never drive it but
    stay visible as a count; first 8 firing entries shown, critical
    severity turns the banner red."""
    firing = []
    total = 0
    silenced = 0
    critical = False
    if alerts is not None:
        for a in alerts:
            if a["state"] == "firing":
                sil = alert_is_silenced(a)
                if sil == True:  # noqa: E712 — transpiled comparison
                    silenced = silenced + 1
                else:
                    total = total + 1
                    if "severity" in a:
                        if a["severity"] == "critical":
                            critical = True
                    if len(firing) < 8:
                        firing.append(
                            {
                                "chip": a["chip"],
                                "rule": a["rule"],
                                "value": a["value"],
                            }
                        )
    warning = True
    if total > 0 and critical == True:  # noqa: E712
        warning = False
    return {
        "show": total > 0 or silenced > 0,
        "warning": warning,
        "firing": firing,
        "firing_total": total,
        "silenced": silenced,
        "truncated": total > 8,
    }


def straggler_banner_model(stragglers):
    """Straggler banner: first 8 firing fleet outliers, each a button
    into its chip's drill-down."""
    entries = []
    total = 0
    if stragglers is not None:
        for s in stragglers:
            if s["state"] == "firing":
                total = total + 1
                if len(entries) < 8:
                    entries.append(s)
    return {
        "show": total > 0,
        "entries": entries,
        "total": total,
        "truncated": total > 8,
    }


# --- binary delta wire decode (TDB1) -----------------------------------------
# The compact binary transport (tpudash/app/wire.py is the encoder and
# the byte-layout reference).  These functions run in the SAME two
# places as apply_delta: directly under the Python test suite, and
# transpiled into the page.  They use the transpiler's extended-but-
# still-value-safe subset (while/break, % and // on NON-NEGATIVE
# operands) and receive bytes as an indexable array of 0..255 integers
# (Python bytes and a JS Uint8Array both read that way).


def rv_read(buf, pos):
    """LEB128 varint at pos[0], advancing pos in place.  The encoder
    keeps every varint below 2^53, so plain float arithmetic is exact
    in both languages."""
    v = 0
    mult = 1
    i = pos[0]
    while True:
        b = buf[i]
        i = i + 1
        v = v + (b % 128) * mult
        if b < 128:
            break
        mult = mult * 128
    pos[0] = i
    return v


def qd_base(p):
    """Scaled-centi base of a previous cell: prev values that are exact
    2-decimal numbers anchor the temporal delta; anything else (null,
    NaN, ±inf, sub-centi precision, outside the exact-integer range)
    anchors at 0.  The ENCODER uses this very function, so both ends
    derive identical bases by construction."""
    if p is None:
        return 0
    b = (p * 100 + 0.5) // 1
    if b / 100 == p:
        if b < 4503599627370496:
            if b > -4503599627370496:
                return b
    return 0


def ieee_read(buf, pos):
    """IEEE-754 binary64 from 8 little-endian bytes, assembled with
    exact float arithmetic (the subset has no DataView): every step is
    a multiply/divide by a power of two or an exact integer sum, so the
    reconstruction is bit-faithful for normals, subnormals and ±0.0;
    any NaN payload decodes to the canonical quiet NaN (JS engines
    canonicalize NaN bits anyway)."""
    i = pos[0]
    lo = buf[i] + buf[i + 1] * 256 + buf[i + 2] * 65536 + buf[i + 3] * 16777216
    hi = (
        buf[i + 4]
        + buf[i + 5] * 256
        + buf[i + 6] * 65536
        + buf[i + 7] * 16777216
    )
    pos[0] = i + 8
    sign = hi // 2147483648
    e = (hi // 1048576) % 2048
    m = (hi % 1048576) * 4294967296 + lo
    v = 0
    if e == 2047:
        if m == 0:
            v = 1e308 * 10
        else:
            v = 1e308 * 10 - 1e308 * 10
    else:
        if e == 0:
            v = m / 4503599627370496.0 * 2.2250738585072014e-308
        else:
            v = 1 + m / 4503599627370496.0
            k = e - 1023
            while k > 0:
                v = v * 2
                k = k - 1
            while k < 0:
                v = v / 2
                k = k + 1
    if sign == 1:
        v = -v
    return v


def qv_read(buf, pos, base100):
    """One quantized cell: code 0 = null, 1 = raw float64 escape,
    2/3 = ±Infinity, 4 = NaN, ≥5 = zigzag centi-delta against base100."""
    n = rv_read(buf, pos)
    if n == 0:
        return None
    if n == 1:
        return ieee_read(buf, pos)
    if n == 2:
        return 1e308 * 10
    if n == 3:
        return -(1e308 * 10)
    if n == 4:
        return 1e308 * 10 - 1e308 * 10
    d = n - 5
    if d % 2 == 1:
        d = -((d + 1) // 2)
    else:
        d = d // 2
    return (base100 + d) / 100.0


def decode_bin_sections(head, buf, prev):
    """Reassemble a value-only delta from one TDB1 binary event: `head`
    (parsed JSON) carries every scalar field verbatim plus the ``_b``
    descriptor; ``buf`` carries heatmap z cells and breakdown values as
    temporal-delta varints against ``prev`` — the client's current
    frame, which both ends hold by the delta contract.  Returns the
    same dict shape as the server's frame_delta, ready for
    apply_delta."""
    d = {}
    hkeys = keys(head)
    for i in range(len(hkeys)):
        if hkeys[i] != "_b":
            d[hkeys[i]] = head[hkeys[i]]
    b = head["_b"]
    pos = [0]
    if "hm" in b:
        shapes = b["hm"]["shapes"]
        changed = b["hm"]["changed"]
        zs = []
        for i in range(len(shapes)):
            prev_z = None
            if "heatmaps" in prev:
                if prev["heatmaps"] is not None:
                    if i < len(prev["heatmaps"]):
                        prev_z = prev["heatmaps"][i]["figure"]["data"][0]["z"]
            if changed[i] == 0:
                zs.append(prev_z)
            else:
                z = []
                r = 0
                while r < shapes[i][0]:
                    prow = None
                    if prev_z is not None:
                        if r < len(prev_z):
                            prow = prev_z[r]
                    row = []
                    c = 0
                    while c < shapes[i][1]:
                        pv = None
                        if prow is not None:
                            if c < len(prow):
                                pv = prow[c]
                        row.append(qv_read(buf, pos, qd_base(pv)))
                        c = c + 1
                    z.append(row)
                    r = r + 1
                zs.append(z)
        d["heatmaps"] = zs
    if "bd" in b:
        bd = {}
        dims = b["bd"]
        for i in range(len(dims)):
            dim = dims[i][0]
            names = dims[i][1]
            cols = dims[i][2]
            pdim = None
            if "breakdown" in prev:
                if prev["breakdown"] is not None:
                    if dim in prev["breakdown"]:
                        pdim = prev["breakdown"][dim]
            masks = []
            for j in range(len(names)):
                masks.append(rv_read(buf, pos))
            counts = []
            for j in range(len(names)):
                counts.append(rv_read(buf, pos))
            rows = {}
            for j in range(len(names)):
                prow = None
                if pdim is not None:
                    if names[j] in pdim:
                        prow = pdim[names[j]]
                row = {}
                bit = 1
                for k in range(len(cols)):
                    if (masks[j] // bit) % 2 == 1:
                        pv = None
                        if prow is not None:
                            if cols[k] in prow:
                                pv = prow[cols[k]]
                        row[cols[k]] = qv_read(buf, pos, qd_base(pv))
                    bit = bit * 2
                row["chips"] = counts[j]
                rows[names[j]] = row
            bd[dim] = rows
        d["breakdown"] = bd
    return d


def numstr(n):
    """Integer → its decimal string.  NOT transpiled: the transpiler
    maps calls to ``numstr(x)`` directly onto JS ``String(x)`` (pyjs),
    and every caller feeds it exact integers (varint/zigzag decodes),
    where ``str(int)`` and ``String(integralNumber)`` print identically.
    The ``int()`` guards the Python side against an integral float
    sneaking in (str(5.0) would print "5.0"; String(5.0) prints "5")."""
    return str(int(n))


def zz_read(buf, pos):
    """Zigzag varint: the signed twin of rv_read (chip-id deltas)."""
    z = rv_read(buf, pos)
    if z % 2 == 1:
        return -((z + 1) // 2)
    return z // 2


def decode_bin_template(head, buf):
    """Reassemble a figure-structure TEMPLATE (TDB1 kind 4) — the
    structural half of a columnar full frame, sent once per cohort
    template epoch.  ``head`` is the parsed container head (mutated in
    place; callers pass a fresh parse), ``buf`` the binary sections.

    The template is the frame minus everything that changes tick to
    tick: scalar fields, z matrices, and figure values are absent and
    arrive in each cfull/delta; the chip table, the selection, and the
    per-slice hover-text / clickable-key / colorscale grids — interned
    in the head so 96 panel figures share 16 slices' grids — are
    rebuilt here.  The returned dict carries the template id under
    ``_tid``; decode_bin_cfull refuses to reassemble against the wrong
    template and strips the marker from the finished frame."""
    b = head["_b"]
    f = {}
    hkeys = keys(head)
    for i in range(len(hkeys)):
        if hkeys[i] != "_b" and hkeys[i] != "tid":
            f[hkeys[i]] = head[hkeys[i]]
    pos = [0]
    chips = []
    if "ch" in b:
        ch = b["ch"]
        slices = ch["slices"]
        hosts = ch["hosts"]
        models = ch["models"]
        prev_id = 0
        i = 0
        while i < ch["n"]:
            s = slices[rv_read(buf, pos)]
            h = hosts[rv_read(buf, pos)]
            m = models[rv_read(buf, pos)]
            prev_id = prev_id + zz_read(buf, pos)
            chips.append(
                {
                    "key": s + "/" + numstr(prev_id),
                    "chip_id": prev_id,
                    "slice": s,
                    "host": h,
                    "model": m,
                }
            )
            i = i + 1
        # selected bitmap, 8 chips per byte, LSB first
        base = pos[0]
        byte = 0
        mask = 1
        i = 0
        while i < len(chips):
            if i % 8 == 0:
                byte = buf[base + i // 8]
                mask = 1
            chips[i]["selected"] = (byte // mask) % 2 == 1
            mask = mask * 2
            i = i + 1
        pos[0] = base + (len(chips) + 7) // 8
        f["chips"] = chips
        if "sel" in b:
            # the selection list: zigzag delta-coded chip indices (a
            # sorted selection deltas to one byte per chip; any order
            # still round-trips exactly)
            selected = []
            prev = 0
            i = 0
            while i < b["sel"]:
                prev = prev + zz_read(buf, pos)
                selected.append(chips[prev]["key"])
                i = i + 1
            f["selected"] = selected
    if "cg" in b:
        # clickable-key customdata grids, interned per slice, cells
        # indexing the chip table (0 = torus padding, k = chips[k-1])
        grids = []
        shapes = b["cg"]
        g = 0
        while g < len(shapes):
            rows = []
            r = 0
            while r < shapes[g][0]:
                row = []
                c = 0
                while c < shapes[g][1]:
                    v = rv_read(buf, pos)
                    if v == 0:
                        row.append(None)
                    else:
                        row.append(chips[v - 1]["key"])
                    c = c + 1
                rows.append(row)
                r = r + 1
            grids.append(rows)
            g = g + 1
        b["cg_grids"] = grids
    if "heatmaps" in f:
        if f["heatmaps"] is not None:
            hms = f["heatmaps"]
            i = 0
            while i < len(hms):
                t = hms[i]["figure"]["data"][0]
                if "customdata" in t:
                    t["customdata"] = b["cg_grids"][t["customdata"]]
                if "text" in t:
                    t["text"] = b["tg"][t["text"]]
                if "colorscale" in t:
                    t["colorscale"] = b["cs"][t["colorscale"]]
                i = i + 1
    f["_tid"] = head["tid"]
    return f


def decode_bin_cfull(head, buf, tpl):
    """One columnar FULL frame (TDB1 kind 5) reassembled onto a FRESH
    copy of its template: the head carries every scalar field plus the
    gauge/trend value patches verbatim, the sections carry z matrices
    and breakdown cells (self-contained, bases 0), and ``tpl`` — which
    the caller re-materializes per call (the page re-parses its cached
    template text; Python deep-copies) — is mutated into the full
    frame.  Returns None when ``tpl`` is not the template this frame
    was encoded against (stale across a cohort epoch): reassembling
    numeric sections onto the wrong structure would render garbage, so
    the caller must fetch a fresh template instead."""
    if "_tid" not in tpl:
        return None
    if tpl["_tid"] != head["tid"]:
        return None
    d = decode_bin_sections(head, buf, {})
    del d["tid"]
    # fields apply_delta doesn't know (federation block, stale marker,
    # future additions) ride the cfull head verbatim and land directly
    handled = {
        "last_updated": 1,
        "timings": 1,
        "source_health": 1,
        "alerts": 1,
        "stragglers": 1,
        "warnings": 1,
        "stats": 1,
        "breakdown": 1,
        "unavailable_panels": 1,
        "average": 1,
        "device_rows": 1,
        "heatmaps": 1,
        "trends": 1,
    }
    dk = keys(d)
    for i in range(len(dk)):
        if dk[i] not in handled:
            tpl[dk[i]] = d[dk[i]]
    apply_delta(tpl, d)
    del tpl["_tid"]
    return tpl


#: everything the page embeds, in dependency order
CLIENT_FUNCTIONS = (
    patch_fig,
    apply_delta,
    stream_event_plan,
    stream_error_plan,
    clamp_frac,
    color_from_scale,
    meter_geometry,
    heat_cell,
    heat_cells,
    spark_points,
    figure_title,
    bar_band_steps,
    figure_render_plan,
    drill_response_plan,
    firing_entries,
    alert_is_silenced,
    drill_view_model,
    silence_toggle_request,
    replay_seek_request,
    replay_toggle_request,
    replay_bar_model,
    stats_table_model,
    breakdown_table_model,
    chip_grid_model,
    alert_banner_model,
    straggler_banner_model,
    rv_read,
    qd_base,
    ieee_read,
    qv_read,
    decode_bin_sections,
    zz_read,
    decode_bin_template,
    decode_bin_cfull,
)
