"""Dashboard page — single self-contained HTML document.

Renders the frame JSON from ``/api/frame``.  Uses plotly.js when the page
can load it — vendored and served by the dashboard itself at the
version-stamped ``PLOTLY_LOCAL_URL`` when the asset is present (zero-egress rich UI,
matching the reference's offline story where plotly is a pinned Python
dependency), with the CDN as last resort; otherwise a built-in
dependency-free renderer draws the same figure dicts as HTML/SVG
(gauges/bars as banded meters, heatmaps as CSS grids), so the dashboard
works fully air-gapped — the figure dicts are the contract, the renderer
is swappable.
"""

PLOTLY_VERSION = "2.32.0"
PLOTLY_CDN_URL = f"https://cdn.plot.ly/plotly-{PLOTLY_VERSION}.min.js"
#: Version-pinned URL: a redeploy that bumps PLOTLY_VERSION changes the
#: URL, so a browser's cached old bundle can never shadow the new one
#: (the asset is served with a long max-age).  The local path and the
#: CDN fallback name the SAME plotly.js version — deploy/fetch_plotly.py
#: pins the wheel whose bundled plotly.js matches, so both load paths
#: render figure dicts identically.
PLOTLY_LOCAL_URL = f"/static/plotly-{PLOTLY_VERSION}.min.js"
#: Tag when no vendored asset exists: CDN or bust (air-gapped → fallback
#: renderer, flagged in the debug strip).
PLOTLY_CDN_TAG = (
    f'<script src="{PLOTLY_CDN_URL}" onerror="window._noPlotly=true"></script>'
)
#: Tag when the dashboard serves the asset itself: local first; if the
#: asset vanished after server start, chain to the CDN and only then give
#: up.  usePlotly() re-checks window.Plotly per render, so a late async CDN
#: arrival upgrades the page on the next frame.
PLOTLY_LOCAL_TAG = (
    f'<script src="{PLOTLY_LOCAL_URL}" onerror="'
    "(function(){var s=document.createElement('script');"
    f"s.src='{PLOTLY_CDN_URL}';"
    "s.onerror=function(){window._noPlotly=true;};"
    'document.head.appendChild(s);})()"></script>'
)


def page_html(local_plotly: bool, wire_format: str = "auto") -> str:
    """The served page: swap the plotly script tag for the local-first
    variant when the server has a vendored bundle to back it, and tell
    the transport layer whether the binary stream is worth attempting
    (TPUDASH_WIRE_FORMAT=json servers refuse it with 406 anyway — the
    flag just skips the doomed probe)."""
    out = PAGE
    if local_plotly:
        out = out.replace(PLOTLY_CDN_TAG, PLOTLY_LOCAL_TAG, 1)
    if wire_format == "json":
        out = out.replace("window._binWire = true;", "window._binWire = false;", 1)
    return out


PAGE = r"""<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>TPU Metrics Dashboard</title>
<script src="https://cdn.plot.ly/plotly-2.32.0.min.js" onerror="window._noPlotly=true"></script>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif; margin: 0;
         background: #f7f9fb; color: #1c2733; }
  header { display: flex; align-items: baseline; gap: 16px; padding: 12px 20px;
           background: #fff; border-bottom: 1px solid #e3e8ee; position: sticky; top: 0; z-index: 5;}
  h1 { font-size: 20px; margin: 0; }
  #last-updated { color: #6b7a8c; font-size: 13px; margin-left: auto; }
  .wrap { padding: 16px 20px; }
  #error-banner { display: none; background: #fdeaea; color: #a8322a;
                  border: 1px solid #e74c3c; border-radius: 6px; padding: 10px 14px; margin-bottom: 12px; }
  #warning-banner { display: none; background: #fdf6e3; color: #8a6d1a;
                    border: 1px solid #e0b93f; border-radius: 6px; padding: 8px 14px; margin-bottom: 12px; }
  #alert-banner { display: none; border-radius: 6px; padding: 8px 14px; margin-bottom: 12px;
                  background: #fdeaea; color: #a8322a; border: 1px solid #e74c3c; }
  #alert-banner.warning { background: #fdf6e3; color: #8a6d1a; border-color: #e0b93f; }
  #straggler-banner { display: none; background: #eef3fb; color: #2a4a78;
                      border: 1px solid #8fa7c4; border-radius: 6px; padding: 8px 14px; margin-bottom: 12px; }
  #straggler-banner button { margin-left: 4px; }
  .controls { display: flex; gap: 18px; align-items: center; margin-bottom: 10px; flex-wrap: wrap;}
  .controls label { font-size: 14px; }
  #chip-grid { display: grid; grid-template-columns: repeat(var(--grid-cols, 4), minmax(120px, 1fr));
               gap: 4px 14px; margin: 8px 0 16px; max-height: 180px; overflow-y: auto;
               border: 1px solid #e3e8ee; border-radius: 6px; padding: 10px; background: #fff;}
  #chip-grid label { font-size: 13px; white-space: nowrap; }
  .slice-bar { grid-column: 1 / -1; display: flex; gap: 6px; flex-wrap: wrap; }
  .row-title { font-size: 16px; font-weight: 600; margin: 14px 0 6px; }
  .panel-row { display: grid; grid-template-columns: repeat(auto-fit, minmax(230px, 1fr)); gap: 10px; }
  .panel { background: #fff; border: 1px solid #e3e8ee; border-radius: 6px; padding: 6px; }
  table { border-collapse: collapse; background: #fff; font-size: 13px; margin-top: 8px;}
  th, td { border: 1px solid #e3e8ee; padding: 5px 10px; text-align: right; }
  th:first-child, td:first-child { text-align: left; }
  .meter { position: relative; height: 26px; border-radius: 4px; overflow: hidden;
           background: #eef2f6; margin-top: 8px; }
  .meter .band { position: absolute; top: 0; bottom: 0; }
  .meter .fill { position: absolute; top: 4px; bottom: 4px; left: 0; border: 1px solid rgba(0,0,0,.55); }
  .fig-title { font-size: 13px; color: #44556a; }
  .fig-value { font-size: 26px; font-weight: 700; }
  .heat { display: grid; gap: 2px; margin-top: 6px; }
  .heat div { aspect-ratio: 1; border-radius: 2px; min-width: 10px; }
  #debug { color: #6b7a8c; font-size: 12px; margin-top: 18px; }
  #drill { display: none; background: #fff; border: 2px solid #8fa7c4;
           border-radius: 8px; padding: 10px 14px; margin: 14px 0; }
  .drill-head { display: flex; align-items: baseline; gap: 12px; }
  .drill-head button { margin-left: auto; }
  .drill-alerts { color: #a8322a; font-size: 13px; margin: 6px 0; }
  .neighbors { font-size: 13px; color: #44556a; margin-top: 8px; }
  .neighbors button { margin-left: 4px; }
  table.links { font-size: 13px; color: #44556a; margin-top: 8px;
    border-collapse: collapse; }
  table.links th, table.links td { border: 1px solid #c7d3e0;
    padding: 2px 8px; text-align: left; }
  tr.link-cold td { background: #fde8e6; color: #a8322a; }
  .hint { color: #6b7a8c; font-size: 12px; }
</style>
</head>
<body>
<header>
  <h1>📊 TPU Metrics Dashboard</h1>
  <span id="last-updated"></span>
</header>
<div class="wrap">
  <div id="error-banner"></div>
  <div id="warning-banner"></div>
  <div id="alert-banner"></div>
  <div id="straggler-banner"></div>
  <div id="gap-note" class="hint" style="display:none; margin-bottom: 8px;"></div>
  <div class="controls">
    <label><input type="checkbox" id="use-gauge" checked> Gauge style (off = bar)</label>
    <button id="select-all">Select all</button>
    <button id="select-none">Clear</button>
    <a id="csv-link" href="/api/export.csv" download="tpudash.csv">Export CSV</a>
    <span id="chip-count"></span>
    <span class="hint">click a heatmap cell for chip detail &middot; shift-click toggles selection</span>
  </div>
  <div id="chip-grid"></div>
  <div id="replay-bar" style="display:none">
    <span class="row-title">Replay</span>
    <button id="replay-pause"></button>
    <input id="replay-slider" type="range" min="0" step="1" style="width: 40%; vertical-align: middle">
    <span id="replay-label" class="hint"></span>
  </div>
  <div id="drill"></div>
  <div id="panels"></div>
  <div class="row-title">Statistics (selected chips)</div>
  <div id="stats"></div>
  <div id="breakdown"></div>
  <div id="debug"></div>
</div>
<script>
const usePlotly = () => !window._noPlotly && window.Plotly;

// Scraped label values (chip keys, slice ids, model names, metric names) are
// untrusted — escape anything interpolated into innerHTML.
const esc = s => String(s).replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));

// ---- dependency-free fallback renderer over the same figure dicts --------
// All decisions — dispatch, parameter extraction, band geometry,
// colorscale selection, cell classification, sparkline scaling — come
// from the GENERATED figure_render_plan / meter_geometry / heat_cell /
// spark_points below; these functions only assemble DOM strings around
// fully-decided plans.
function renderMeter(el, plan) {
  const g = meter_geometry(plan.value, plan.max, plan.steps || []);
  let bands = '';
  for (const b of g.bands) {
    bands += `<div class="band" style="left:${b.left}%;width:${b.width}%;background:${b.color}"></div>`;
  }
  el.innerHTML = `<div class="fig-title">${esc(plan.title)}</div>
    <div class="fig-value" style="color:${esc(plan.color)}">${(+plan.value).toFixed(1)}</div>
    <div class="meter">${bands}<div class="fill" style="width:${g.pct}%;background:${esc(plan.color)}"></div></div>
    <div class="fig-title">max ${+plan.max}</div>`;
}

function renderHeatFallback(el, plan) {
  let cells = '';
  // cell classification, key alignment, and grid walking are the
  // GENERATED heat_cells; the flat list wraps into rows via the grid
  for (const cell of heat_cells(plan)) {
    if (cell.kind === 'blank') {
      cells += '<div style="background:transparent"></div>';
    } else if (cell.kind === 'deselected') {
      // deselected chips keep their key so a click re-selects them
      cells += `<div style="background:#e3e9f0;cursor:pointer" data-key="${esc(cell.key)}" title="deselected"></div>`;
    } else {
      cells += `<div style="background:${cell.color};cursor:pointer" title="${(+cell.v).toFixed(1)}"` +
               (cell.key ? ` data-key="${esc(cell.key)}"` : '') + `></div>`;
    }
  }
  el.innerHTML = `<div class="fig-title">${esc(plan.title)}</div>
    <div class="heat" style="grid-template-columns:repeat(${+plan.cols},1fr)">${cells}</div>`;
  el.querySelector('.heat').addEventListener('click', e => {
    const key = e.target.getAttribute && e.target.getAttribute('data-key');
    if (!key) return;
    if (e.shiftKey) post('/api/select', {toggle: key});
    else showChip(key);
  });
}

function renderLineFallback(el, plan) {
  const W = 240, H = 64;
  let pts = '';
  for (const p of spark_points(plan.ys, plan.ymax, W, H)) {
    pts += `${p[0].toFixed(1)},${p[1].toFixed(1)} `;
  }
  el.innerHTML = `<div class="fig-title">${esc(plan.title)}</div>
    <svg viewBox="0 0 ${W} ${H}" style="width:100%;height:64px;background:#f2f6fa;border-radius:4px">
      <polyline points="${pts}" fill="none" stroke="${esc(plan.color)}" stroke-width="2"/></svg>
    <div class="fig-title">now ${(+plan.last).toFixed(1)} · max ${+plan.ymax}</div>`;
}

function renderFigure(el, fig) {
  if (usePlotly()) {
    Plotly.react(el, fig.data, fig.layout, {displayModeBar: false});
    const tr = fig.data[0];
    if (tr.type === 'heatmap' && tr.customdata && !el._heatClick) {
      el._heatClick = true;  // panel divs are rebuilt per frame
      el.on('plotly_click', ev => {
        const key = ev.points && ev.points[0] && ev.points[0].customdata;
        if (!key) return;
        if (ev.event && ev.event.shiftKey) post('/api/select', {toggle: key});
        else showChip(key);
      });
    }
    return;
  }
  const plan = figure_render_plan(fig);
  if (plan.kind === 'meter') renderMeter(el, plan);
  else if (plan.kind === 'heat') renderHeatFallback(el, plan);
  else if (plan.kind === 'spark') renderLineFallback(el, plan);
}

// ---- state + API ----------------------------------------------------------
// auth: when the server runs with TPUDASH_AUTH_TOKEN, the operator opens
// the page as /?token=....  fetch() calls carry it as an Authorization
// header; ONLY the EventSource stream uses the query param (EventSource
// cannot set headers, and the server accepts ?token= on /api/stream alone
// so the secret stays out of access logs for every other route).
const TOKEN = new URLSearchParams(location.search).get('token');
function streamUrl(url) {
  if (!TOKEN) return url;
  return url + (url.includes('?') ? '&' : '?') + 'token=' + encodeURIComponent(TOKEN);
}
function authHeaders(extra) {
  const h = Object.assign({}, extra || {});
  if (TOKEN) h['Authorization'] = 'Bearer ' + TOKEN;
  return h;
}

function postJson(url, body) {
  return fetch(url, {method: 'POST',
                     headers: authHeaders({'Content-Type': 'application/json'}),
                     body: JSON.stringify(body)});
}
async function post(url, body) {
  await postJson(url, body);
  await refresh();
}

// ---- per-chip drill-down (click a heatmap cell) ---------------------------
let drillKey = null;

async function showChip(key) {
  drillKey = key;
  await refreshDrill();
  const el = document.getElementById('drill');
  if (el.style.display !== 'none') el.scrollIntoView({behavior: 'smooth', block: 'nearest'});
}

function closeDrill() {
  drillKey = null;
  const el = document.getElementById('drill');
  el.style.display = 'none';
  el.innerHTML = '';
}

async function refreshDrill() {
  const key = drillKey;  // snapshot: user may close / switch mid-fetch
  if (!key) return;
  let resp = null;
  try {
    resp = await fetch('/api/chip?key=' + encodeURIComponent(key),
                       {headers: authHeaders()});
  } catch (e) { /* transient */ }
  // the stale/404/transient policy is the GENERATED drill_response_plan
  const plan = drill_response_plan(key, drillKey, resp ? resp.status : 0, !resp);
  if (plan === 'drop' || plan === 'keep') return;
  if (plan === 'close') { closeDrill(); return; /* chip left the fleet */ }
  const detail = await resp.json();
  if (drillKey === key) renderDrill(detail);
}

function renderDrill(d) {
  const el = document.getElementById('drill');
  el.style.display = 'block';
  // firing filters, acknowledge-button labels, cold-link flags, and
  // placeholder decisions are the GENERATED drill_view_model
  const m = drill_view_model(d);
  let html = `<div class="drill-head"><span class="row-title">TPU ${+d.chip_id}` +
    ` &mdash; ${esc(d.slice)} / ${esc(d.host)} (${esc(d.model)})</span>` +
    `<button id="drill-close">close</button></div>`;
  if (m.show_alerts) {
    // each firing alert gets a one-click acknowledge (1h silence) /
    // unsilence toggle — the operator workflow, not just the signal
    html += `<div class="drill-alerts">⚠ ` +
      m.alerts.map((a, i) => esc(a.rule) + (a.silenced ? ' 🔇' : '') +
                 ' (=' + (+a.value) + ') ' +
                 `<button class="silence-btn" data-i="${i}">` +
                 a.button_label + '</button>'
                ).join(' · ') + '</div>';
  }
  if (m.show_stragglers) {
    html += `<div class="drill-alerts" style="color:#2a4a78">🐢 straggler: ` +
      m.stragglers.map(s => esc(s.column) + ' ' + (+s.value) + ' vs fleet ' +
                  (+s.median) + ' (z=' + (+s.z) + ')').join(' · ') + '</div>';
  }
  html += '<div class="panel-row" id="drill-gauges"></div>';
  html += '<div class="panel-row" id="drill-trends"></div>';
  if (m.show_links) {
    // direction-resolved per-link table: the failing CABLE, with the
    // chip on its far end one click away
    html += '<table class="links"><tr><th>link</th><th>GB/s</th><th>far end</th></tr>' +
      m.links.map(l =>
        `<tr${l.cold ? ' class="link-cold"' : ''}><td>${esc(l.dir)}` +
        (l.cold ? ' 🐢' : '') + '</td><td>' +
        (l.gbps === null ? '—' : (+l.gbps)) + '</td><td>' +
        (l.neighbor !== null ? `<button data-chip="${esc(l.neighbor)}">${esc(l.neighbor)}</button>` : '—') +
        '</td></tr>').join('') + '</table>';
  }
  if (m.show_neighbors) {
    html += `<div class="neighbors">ICI neighbors:` +
      m.neighbors.map(n => `<button data-chip="${esc(n)}">${esc(n)}</button>`).join('') +
      '</div>';
  }
  el.innerHTML = html;
  figureCells(document.getElementById('drill-gauges'), d.figures);
  figureCells(document.getElementById('drill-trends'), d.trends);
  document.getElementById('drill-close').addEventListener('click', closeDrill);
  for (const btn of el.querySelectorAll('.neighbors button, table.links button')) {
    btn.addEventListener('click', () => showChip(btn.getAttribute('data-chip')));
  }
  for (const btn of el.querySelectorAll('.silence-btn')) {
    btn.addEventListener('click', async () => {
      const a = m.alerts[+btn.getAttribute('data-i')];
      const req = silence_toggle_request(a.rule, a.chip, a.silenced);
      await postJson(req.path, req.body);
      refreshDrill(); refresh();
    });
  }
}

function renderChips(chips) {
  const grid = document.getElementById('chip-grid');
  grid.innerHTML = '';
  // grouping/count decisions are the GENERATED chip_grid_model
  const model = chip_grid_model(chips);
  if (model.show_bar) {
    // multi-slice fleets: one-click slice selection above the grid
    const bar = document.createElement('div');
    bar.className = 'slice-bar';
    for (const s of model.slices) {
      const btn = document.createElement('button');
      btn.textContent = `${s.slice} (${s.keys.length})`;
      btn.title = `select only ${s.slice}`;
      btn.addEventListener('click', () => post('/api/select', {selected: s.keys}));
      bar.appendChild(btn);
    }
    grid.appendChild(bar);
  }
  for (const c of chips) {
    const id = 'chip_checkbox_' + c.key;
    const label = document.createElement('label');
    label.innerHTML = `<input type="checkbox" id="${esc(id)}" ${c.selected ? 'checked' : ''}> ` +
                      `TPU ${+c.chip_id} <small>(${esc(c.model)}, ${esc(c.slice)})</small>`;
    label.querySelector('input').addEventListener('change',
      () => post('/api/select', {toggle: c.key}));
    grid.appendChild(label);
  }
  document.getElementById('chip-count').textContent =
    model.selected + ' / ' + model.total + ' chips selected';
}

function figureCells(row, figs) {
  for (const f of figs || []) {
    const cell = document.createElement('div');
    cell.className = 'panel';
    row.appendChild(cell);
    renderFigure(cell, f.figure);
  }
}

function panelRow(container, rowTitle, figures) {
  const title = document.createElement('div');
  title.className = 'row-title'; title.textContent = rowTitle;
  container.appendChild(title);
  const row = document.createElement('div');
  row.className = 'panel-row';
  figureCells(row, figures);
  container.appendChild(row);
}

function renderBreakdown(bd, panelSpecs) {
  // column selection / titles / row cells are the GENERATED
  // breakdown_table_model; this only prints the table
  const el = document.getElementById('breakdown');
  let html = '';
  for (const tbl of breakdown_table_model(bd || null, panelSpecs || null)) {
    html += `<div class="row-title">${esc(tbl.title)}</div><table><tr><th>${esc(tbl.head)}</th><th>chips</th>`;
    for (const p of tbl.cols) html += `<th>${esc(p.title)}</th>`;
    html += '</tr>';
    for (const row of tbl.rows) {
      html += `<tr><td>${esc(row[0])}</td>`;
      for (let i = 1; i < row.length; i++) {
        html += `<td>${row[i] === null ? '—' : +row[i]}</td>`;
      }
      html += '</tr>';
    }
    html += '</table>';
  }
  el.innerHTML = html;
}

function renderStats(stats) {
  const el = document.getElementById('stats');
  const model = stats_table_model(stats);
  if (!model.metrics.length) { el.innerHTML = '<em>no data</em>'; return; }
  let html = '<table><tr><th>metric</th>' +
    model.cols.map(k => `<th>${k}</th>`).join('') + '</tr>';
  for (let i = 0; i < model.metrics.length; i++) {
    html += `<tr><td>${esc(model.metrics[i])}</td>` +
      model.rows[i].map(v => `<td>${v === null ? '—' : +v}</td>`).join('') + '</tr>';
  }
  el.innerHTML = html + '</table>';
}

async function refresh() {
  let frame;
  try {
    frame = await (await fetch('/api/frame', {headers: authHeaders()})).json();
  } catch (e) {
    showError('Dashboard server unreachable: ' + e);
    if (!streaming && !timer) timer = setInterval(refresh, 5000);  // keep retrying
    return;
  }
  applyFrame(frame);
}

function applyFrame(frame) {
  document.getElementById('last-updated').textContent = 'Last updated: ' + frame.last_updated;
  if (!streaming && !timer) timer = setInterval(refresh, (frame.refresh_interval || 5) * 1000);
  showError(frame.error);
  showWarnings(frame.warnings);
  showAlerts(frame.alerts);
  showStragglers(frame.stragglers);
  if (frame.error) return;  // keep last good panels (reference skips the cycle)
  document.getElementById('use-gauge').checked = frame.use_gauge;
  renderChips(frame.chips);
  const panels = document.getElementById('panels');
  panels.innerHTML = '';
  if (frame.average) panelRow(panels, frame.average.title, frame.average.figures);
  if (frame.trends && frame.trends.length) panelRow(panels, 'Trends', frame.trends);
  for (const row of frame.device_rows || []) panelRow(panels, row.title, row.figures);
  // heatmaps group per panel metric
  const heat = frame.heatmaps || [];
  if (heat.length) panelRow(panels, 'Topology heatmaps', heat);
  renderStats(frame.stats || {});
  renderBreakdown(frame.breakdown, frame.panel_specs);
  showPanelGaps(frame.unavailable_panels);
  if (drillKey) refreshDrill();  // keep the open chip detail live
  if (replayActive !== false) pollReplay();  // keep the scrub position current
  const t = frame.timings || {};
  document.getElementById('debug').textContent =
    'Debug: frames=' + (t.frames || 0) +
    (t.total ? (', scrape→render p50=' + t.total.p50_ms.toFixed(1) + ' ms') : '') +
    (streaming ? ' · live (SSE)' : ' · polling') +
    (window._noPlotly ? ' · fallback renderer (plotly.js unavailable)' : '');
}

// ---- transport: SSE push with polling fallback ----------------------------
// Steady-state ticks arrive as value-only deltas (kind: "delta") patched
// into the last full frame.  apply_delta / stream_event_plan /
// stream_error_plan below are GENERATED from the fuzz-tested Python
// client logic (tpudash/app/clientlogic.py) — edit the Python, never
// this block; tests/test_client_parity.py pins the embedding.
let lastFrame = null;

/*__GENERATED_CLIENT__*/

// ---- binary transport (TDB1, tpudash/app/wire.py) -------------------------
// The steady-state delta stream in the compact binary encoding:
// ~3-5x fewer wire bytes at fleet scale.  DECODING is the generated
// decode_bin_sections above (single source with the server and the test
// suite); this block is only framing glue — fetch-streaming, event
// splitting, container parsing.  Any failure before the first event
// falls back permanently to the JSON EventSource path below; failures
// after that reconnect with ?last_id= resume.
window._binWire = true;
let binFailed = false;
let binAckId = null;
// the cached figure-structure template (TDB1 kind 4): head JSON text +
// section bytes, re-materialized FRESH per columnar full frame (each
// cfull mutates its copy into the frame).  binTplId rides reconnect
// URLs so a resume whose template is still current skips the bytes; a
// stale id just means the server sends a fresh template first.
let binTplHead = null, binTplPayload = null, binTplId = null;

function parseTDB1(body, td) {
  if (body.length < 12 || td.decode(body.subarray(0, 4)) !== 'TDB1')
    throw new Error('bad TDB1 container');
  const dv = new DataView(body.buffer, body.byteOffset);
  const hlen = dv.getUint32(8, true);
  return {kind: body[5],
          headText: td.decode(body.subarray(12, 12 + hlen)),
          payload: body.subarray(16 + hlen)};
}

function startBinStream() {
  let gotEvent = false;
  const base = streamUrl('/api/stream');
  const url = base + (base.indexOf('?') >= 0 ? '&' : '?') + 'format=bin' +
    (binAckId ? '&last_id=' + encodeURIComponent(binAckId) : '') +
    (binTplId ? '&tpl=' + encodeURIComponent(binTplId) : '');
  (async () => {
    const resp = await fetch(url, {headers: authHeaders()});
    if (!resp.ok || !resp.body) throw new Error('HTTP ' + resp.status);
    const reader = resp.body.getReader();
    const td = new TextDecoder('utf-8');
    let buf = new Uint8Array(0);
    for (;;) {
      const chunk = await reader.read();
      if (chunk.done) throw new Error('stream ended');
      if (buf.length === 0) { buf = chunk.value; }
      else {
        const nb = new Uint8Array(buf.length + chunk.value.length);
        nb.set(buf); nb.set(chunk.value, buf.length); buf = nb;
      }
      for (;;) {
        if (buf.length < 8) break;
        if (buf[0] !== 84 || buf[1] !== 69) throw new Error('bad framing');
        const etype = buf[2], idlen = buf[3];
        if (buf.length < 8 + idlen) break;
        const dv = new DataView(buf.buffer, buf.byteOffset);
        const blen = dv.getUint32(4 + idlen, true);
        if (buf.length < 8 + idlen + blen) break;
        const id = td.decode(buf.subarray(4, 4 + idlen));
        const body = buf.subarray(8 + idlen, 8 + idlen + blen);
        buf = buf.slice(8 + idlen + blen);
        gotEvent = true;
        streaming = true;
        if (timer) { clearInterval(timer); timer = null; }
        if (id) binAckId = id;
        if (etype === 4) {              // figure template (TDB1 kind 4)
          const t = parseTDB1(body, td);
          binTplHead = t.headText;
          binTplPayload = t.payload.slice();
          binTplId = JSON.parse(t.headText).tid;
          continue;
        } else if (etype === 1) {       // full frame
          if (body.length >= 4 && td.decode(body.subarray(0, 4)) === 'TDB1') {
            // columnar cfull: numeric sections onto a FRESH copy of the
            // cached template (decode refuses a template mismatch —
            // never garbage — and the server always sends the matching
            // template first, so a null here means a broken stream)
            const c = parseTDB1(body, td);
            let frame = null;
            if (binTplHead !== null) {
              const tpl = decode_bin_template(
                JSON.parse(binTplHead), binTplPayload);
              frame = decode_bin_cfull(
                JSON.parse(c.headText), c.payload, tpl);
            }
            if (frame === null) {
              binTplHead = binTplPayload = binTplId = null;
              throw new Error('columnar frame without its template');
            }
            lastFrame = frame;
          } else {
            lastFrame = JSON.parse(td.decode(body));  // JSON fallback body
          }
        } else if (etype === 2) {       // binary delta (TDB1 container)
          if (lastFrame === null) { refresh(); continue; }
          const d = parseTDB1(body, td);
          const delta = decode_bin_sections(
            JSON.parse(d.headText), d.payload, lastFrame);
          lastFrame = apply_delta(lastFrame, delta);
        } else {
          continue;                     // keepalive
        }
        if (!document.hidden) applyFrame(lastFrame);
      }
    }
  })().catch(() => {
    streaming = false;
    if (!gotEvent) binFailed = true;    // binary refused/broken → JSON path
    if (!timer) timer = setInterval(refresh, 5000);
    setTimeout(startStream, binFailed ? 0 : 5000);
  });
}

function startStream() {
  if (window._binWire && !binFailed && window.fetch && window.TextDecoder) {
    startBinStream();
    return;
  }
  if (!window.EventSource) return;  // old browser → polling stays active
  const es = new EventSource(streamUrl('/api/stream'));
  es.onmessage = e => {
    streaming = true;
    if (timer) { clearInterval(timer); timer = null; }
    const msg = JSON.parse(e.data);
    const plan = stream_event_plan(msg.kind, lastFrame !== null);
    if (plan === 'refetch') { refresh(); return; }  // missed the full frame
    lastFrame = plan === 'delta' ? apply_delta(lastFrame, msg) : msg;
    // keep the model current but skip DOM/plot work for hidden tabs —
    // the visibilitychange handler re-renders on return
    if (!document.hidden) applyFrame(lastFrame);
  };
  es.onerror = () => {
    // server restart / proxy hiccup: the recovery policy is the
    // generated stream_error_plan (see clientlogic.py for the why)
    streaming = false;
    const plan = stream_error_plan(
      es.readyState === EventSource.CLOSED, timer !== null);
    if (plan.poll_ms > 0) timer = setInterval(refresh, plan.poll_ms);
    if (plan.reopen_ms > 0) setTimeout(startStream, plan.reopen_ms);
  };
}

document.getElementById('use-gauge').addEventListener('change',
  e => post('/api/style', {use_gauge: e.target.checked}));
// a plain <a href> navigation cannot send the Authorization header, so the
// export fetches the CSV and hands the browser a blob download instead
document.getElementById('csv-link').addEventListener('click', async e => {
  e.preventDefault();
  const resp = await fetch('/api/export.csv', {headers: authHeaders()});
  if (!resp.ok) { showError('CSV export failed: HTTP ' + resp.status); return; }
  const url = URL.createObjectURL(await resp.blob());
  const a = document.createElement('a');
  a.href = url; a.download = 'tpudash.csv';
  a.click();
  URL.revokeObjectURL(url);
});
document.getElementById('select-all').addEventListener('click',
  () => post('/api/select', {all: true}));
document.getElementById('select-none').addEventListener('click',
  () => post('/api/select', {none: true}));

// ---- replay time-travel (source=replay only) ------------------------------
// A recorded incident can be scrubbed back and forth: the bar appears when
// /api/replay answers, the slider seeks by snapshot index, pause holds the
// current snapshot instead of auto-advancing.  Tri-state: null = unknown
// (keep probing each frame — a transient error must not permanently hide
// or freeze the bar), true = replaying, false = definitively not (404).
let replayActive = null;

// scrub/pause → request bodies are the GENERATED replay_*_request
document.getElementById('replay-slider').addEventListener('change',
  async e => {
    const r = await postJson('/api/replay', replay_seek_request(+e.target.value));
    if (r.ok) { renderReplayPosition(await r.json()); refresh(); }
  });
document.getElementById('replay-pause').addEventListener('click',
  async () => {
    const r = await postJson('/api/replay', replay_toggle_request(replayPaused));
    if (r.ok) renderReplayPosition(await r.json());
  });

function renderReplayPosition(pos) {
  banner('replay-bar', true);
  const slider = document.getElementById('replay-slider');
  const m = replay_bar_model(pos, document.activeElement === slider);
  replayPaused = m.paused;
  slider.max = m.max;
  if (m.set_value !== null) slider.value = m.set_value;
  document.getElementById('replay-pause').textContent = m.paused ? '▶ resume' : '⏸ pause';
  document.getElementById('replay-label').textContent =
    (m.pos === null ? '—' : m.pos) + '/' + m.total +
    (m.ts !== null ? ' · ' + new Date(m.ts * 1000).toLocaleTimeString() : '');
}
let replayPaused = false;

async function pollReplay() {
  try {
    const r = await fetch('/api/replay', {headers: authHeaders()});
    if (r.status === 404) { replayActive = false; return; }
    if (!r.ok) return;  // transient: keep the last state, retry next frame
    replayActive = true;
    renderReplayPosition(await r.json());
  } catch (e) { /* transient */ }
}
pollReplay();

function banner(id, show) {
  const b = document.getElementById(id);
  b.style.display = show ? 'block' : 'none';
  return b;
}

function showError(msg) {
  banner('error-banner', !!msg).textContent = msg || '';
}

function showAlerts(list) {
  // silenced (acknowledged) alerts never drive the banner; membership,
  // severity class, truncation, and the silenced count all come from
  // the GENERATED alert_banner_model
  const m = alert_banner_model(list || null);
  const b = banner('alert-banner', m.show);
  if (!m.show) return;
  b.className = m.warning ? 'warning' : '';
  b.textContent = (m.firing_total
    ? '\u26a0 ' + m.firing_total + ' alert(s): ' + m.firing
      .map(a => a.chip + ' ' + a.rule + ' (=' + a.value + ')').join(' \u00b7 ') +
      (m.truncated ? ' \u2026' : '')
    : '') +
    (m.silenced ? ' \ud83d\udd07 ' + m.silenced + ' silenced' : '');
}

function showStragglers(list) {
  // fleet outliers gating SPMD lockstep (tpudash.stragglers) — each chip
  // is a button into its drill-down; membership and truncation are the
  // GENERATED straggler_banner_model
  const m = straggler_banner_model(list || null);
  const b = banner('straggler-banner', m.show);
  if (!m.show) return;
  b.innerHTML = '🐢 ' + m.total + ' straggler(s): ' +
    m.entries.map(s =>
      `<button data-chip="${esc(s.chip)}">${esc(s.chip)}</button> ` +
      `${esc(s.column)} ${+s.value} vs fleet ${+s.median} (z=${+s.z})`
    ).join(' · ') + (m.truncated ? ' …' : '');
  for (const btn of b.querySelectorAll('button')) {
    btn.addEventListener('click', () => showChip(btn.getAttribute('data-chip')));
  }
}

function showPanelGaps(list) {
  // a core panel the source can't feed is declared, never silently absent
  const b = banner('gap-note', !!(list && list.length));
  if (list && list.length) {
    b.innerHTML = 'Hidden panels: ' + list.map(g =>
      `<span title="${esc(g.reason)}">${esc(g.title)}</span>`).join(' · ') +
      ' <small>(hover for why)</small>';
  }
}

function showWarnings(list) {
  const b = banner('warning-banner', !!(list && list.length));
  if (list && list.length) b.textContent = 'Degraded: ' + list.join(' · ');
}

document.addEventListener('visibilitychange', () => {
  if (!document.hidden && lastFrame) applyFrame(lastFrame);
});

let timer = null;
let streaming = false;
refresh();
startStream();
</script>
</body>
</html>
"""

# The transport-critical client functions are generated from the
# fuzz-tested Python source of truth (clientlogic.py) at import time —
# see pyjs.py for why this beats a hand-maintained JS mirror.
from tpudash.app.clientlogic import CLIENT_FUNCTIONS  # noqa: E402
from tpudash.app.pyjs import transpile_functions  # noqa: E402

GENERATED_CLIENT_JS = transpile_functions(CLIENT_FUNCTIONS)
PAGE = PAGE.replace("/*__GENERATED_CLIENT__*/", GENERATED_CLIENT_JS)
