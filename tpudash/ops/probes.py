"""Single-chip probes: MXU throughput, HBM bandwidth, HBM occupancy.

Design notes (TPU-first):
- The MXU probe is a chain of large bf16 matmuls under one jit — static
  shapes, no host round-trips inside the loop (lax.fori_loop), so XLA tiles
  the whole chain onto the MXU.  Achieved TFLOP/s ÷ the generation's peak
  gives the TensorCore-utilization % the dashboard displays.
- The HBM probe is a Pallas grid kernel streaming a large buffer through
  VMEM (read + write ≈ 2× traffic); on non-TPU backends it runs in
  interpret mode so tests stay cluster-free.

Timing methodology: on tunneled/async device platforms,
``block_until_ready`` can return at dispatch time, and any single
measurement includes a fixed host↔device round-trip.  Every probe therefore
(a) reduces its result to a scalar fetched to the host — a true completion
barrier — and (b) measures at two work multiples and uses the DELTA, which
cancels the fixed round-trip overhead:

    value = extra_work / (t(k2) - t(k1))
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

_MIN_DELTA_S = 1e-5  # guard against clock noise producing absurd rates


def _dev() -> jax.Device:
    return jax.local_devices()[0]


def device_info() -> dict:
    """Platform/device identity for labels (the probe-source analogue of the
    reference's card_model label, app.py:191-201)."""
    d = _dev()
    return {
        "platform": d.platform,
        "device_kind": getattr(d, "device_kind", str(d)),
        "num_local_devices": jax.local_device_count(),
    }


@dataclass(frozen=True)
class ProbeResult:
    value: float      # headline number (TFLOP/s or GB/s or µs)
    #: the rate denominator: for delta-timed probes, the median paired
    #: (large − small) work delta in wall seconds — NOT the probe's total
    #: wall cost; for single-shot probes, that run's wall time.
    elapsed_s: float
    detail: dict


def _timed_scalar(fn, *args, trials: int = 2) -> float:
    """Best-of-N wall time of fn(*args) where fn returns a scalar jax array;
    float() forces a device→host readback (true completion barrier)."""
    float(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _delta_time(fn_small, fn_large, pairs: int = 5) -> float:
    """Median of paired (large - small) wall-time deltas.

    Each pair times the small and large work variants back to back, so slow
    drift (tunnel congestion, host load) affects both sides of a pair
    equally and cancels; the median rejects a pair hit by a one-off spike —
    a lone spike on either side otherwise produces absurd rates.
    """
    float(fn_small())  # compile + warm both variants
    float(fn_large())
    deltas = []
    for _ in range(pairs):
        t0 = time.perf_counter()
        float(fn_small())
        t1 = time.perf_counter()
        float(fn_large())
        t2 = time.perf_counter()
        deltas.append((t2 - t1) - (t1 - t0))
    deltas.sort()
    return max(deltas[len(deltas) // 2], _MIN_DELTA_S)


# --- MXU throughput ---------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("iters",))
def _matmul_chain_sum(x: jax.Array, w: jax.Array, iters: int) -> jax.Array:
    """iters dependent matmuls; data dependence defeats CSE/folding; scalar
    output forces completion when fetched."""

    def body(_, acc):
        return jnp.dot(acc, w, preferred_element_type=jnp.bfloat16)

    return jnp.sum(lax.fori_loop(0, iters, body, x).astype(jnp.float32))


def matmul_flops_probe(
    size: int = 2048,
    iters: int = 8,
    dtype=jnp.bfloat16,
    device: "jax.Device | None" = None,
) -> ProbeResult:
    """Achieved matmul TFLOP/s on one chip (delta-timed).

    size is rounded up to an MXU-friendly multiple of 256; measured at
    ``iters`` and ``3·iters`` chained (size×size) matmuls — 2·size³ FLOPs
    each — and rated on the difference.  ``device`` selects which local
    chip runs the probe (default: first).
    """
    size = max(256, (size + 255) // 256 * 256)
    iters = max(1, iters)
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (size, size), dtype=dtype)
    # small weights keep the chain numerically tame over many iterations
    w = jax.random.normal(kw, (size, size), dtype=dtype) * (size**-0.5)
    if device is not None:
        x, w = jax.device_put(x, device), jax.device_put(w, device)

    dt = _delta_time(
        lambda: _matmul_chain_sum(x, w, iters),
        lambda: _matmul_chain_sum(x, w, 3 * iters),
    )
    flops = 2.0 * size**3 * (2 * iters)
    return ProbeResult(
        value=flops / dt / 1e12,
        elapsed_s=dt,
        detail={"size": size, "iters": iters, "dtype": jnp.dtype(dtype).name},
    )


# --- HBM bandwidth (Pallas) -------------------------------------------------

def _copy_kernel(in_ref, out_ref):
    out_ref[:] = in_ref[:]


def _hbm_stream_once(x: jax.Array, block_rows: int):
    from jax.experimental import pallas as pl

    rows, cols = x.shape
    return pl.pallas_call(
        _copy_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        interpret=jax.default_backend() != "tpu",
    )(x)


@functools.partial(jax.jit, static_argnames=("block_rows", "repeats"))
def _hbm_stream_sum(x: jax.Array, block_rows: int, repeats: int) -> jax.Array:
    def body(_, acc):
        return _hbm_stream_once(acc, block_rows)

    return jnp.sum(lax.fori_loop(0, repeats, body, x)[0, :8])


def hbm_bandwidth_probe(
    mb: int = 256,
    block_rows: int = 1024,
    k1: int = 1,
    k2: int = 9,
    device: "jax.Device | None" = None,
) -> ProbeResult:
    """Achieved HBM streaming bandwidth (GB/s), counting read + write.

    Buffer is (rows, 1024) float32 sized to ``mb`` MiB, streamed block-wise
    through VMEM (block_rows×1024×4B = 4 MiB/block by default, well under
    the ~16 MiB VMEM budget); delta-timed at ``k1`` vs ``k2`` passes.  The
    (k2-k1) contrast must represent several milliseconds of traffic or the
    delta drowns in host↔device jitter — at 256 MiB × 8 extra passes ×
    read+write ≈ 4 GiB, ~5 ms on a v5e.
    """
    if k2 <= k1:
        raise ValueError("k2 must exceed k1")
    cols = 1024
    rows = max(block_rows, (mb * 1024 * 1024) // (cols * 4))
    rows = (rows // block_rows) * block_rows
    x = jnp.ones((rows, cols), jnp.float32)
    if device is not None:
        x = jax.device_put(x, device)

    dt = _delta_time(
        lambda: _hbm_stream_sum(x, block_rows, k1),
        lambda: _hbm_stream_sum(x, block_rows, k2),
    )
    nbytes = x.size * 4
    return ProbeResult(
        value=2.0 * nbytes * (k2 - k1) / dt / 1e9,  # (read+write) per pass
        elapsed_s=dt,
        detail={"mb": nbytes // (1024 * 1024), "block_rows": block_rows,
                "k1": k1, "k2": k2},
    )


# --- HBM occupancy ----------------------------------------------------------

def hbm_memory_stats(device: "jax.Device | None" = None) -> dict:
    """Allocator view of one device's HBM: {used_bytes, total_bytes} — the
    probe-source feed for the tpu_hbm_* series.  Backends without
    memory_stats (CPU) return zeros; callers treat 0 total as "unknown"."""
    dev = device if device is not None else _dev()
    try:
        stats = dev.memory_stats() or {}
    except Exception:  # some backends raise instead of returning None
        stats = {}
    return {
        "used_bytes": float(stats.get("bytes_in_use", 0)),
        "total_bytes": float(stats.get("bytes_limit", 0)),
    }
