"""Project-invariant static analysis and runtime concurrency sanitizing.

PR 1 made tpudash genuinely concurrent: per-endpoint circuit breakers,
in-flight child tracking, shared service state mutated from fetch threads
behind a publish lock.  That regime has invariants the interpreter cannot
enforce and review alone will not keep enforced — so this package does:

- :mod:`tpudash.analysis.lint` — ``python -m tpudash.analysis.lint`` — an
  AST linter that walks the package and enforces named, testable project
  rules (monotonic clocks in deadline arithmetic, env reads only through
  the config registry, no blocking calls under a held ``threading.Lock``,
  no swallowed ``BaseException`` handlers, every ``TPUDASH_*`` variable
  declared and documented).  Exits non-zero naming rule and ``file:line``.

- :mod:`tpudash.analysis.racecheck` — a test-time sanitizer that
  monkeypatches ``threading.Lock``/``RLock`` to record acquisition order
  per thread, detects lock-order inversions (potential deadlocks) across
  the breaker/multi-source/service/session layers, and flags writes to
  registered shared attributes performed without their guarding lock.

- :mod:`tpudash.analysis.asynccheck` — ``python -m
  tpudash.analysis.asynccheck`` — event-loop hygiene, both halves: an
  interprocedural static pass (blocking calls reachable from ``async
  def`` without an executor boundary, ``await`` under a held sync lock,
  fire-and-forget task spawns) and a runtime loop-lag sanitizer
  (:class:`~tpudash.analysis.asynccheck.LoopLagMonitor`) whose
  ``loop_lag_ms`` counters surface on ``/api/timings`` and ``/healthz``
  and run in pytest behind ``TPUDASH_LOOPCHECK=1``.

- :mod:`tpudash.analysis.leakcheck` — ``python -m
  tpudash.analysis.leakcheck`` — resource lifetimes, both halves: an
  interprocedural static pass (sockets/files/memfds/executors/client
  sessions that escape their creating scope un-closed on some path —
  including connect/handshake error paths — non-daemon threads without
  a join handle, long-lived tasks/timers without a cancellation owner,
  ``finally:`` cleanup that can mask the in-flight exception) and a
  runtime FD/thread/task census
  (:class:`~tpudash.analysis.leakcheck.ResourceCensus`) that attributes
  growth to creation sites, surfaces ``{fds, threads, tasks,
  high_water}`` on ``/api/timings`` and ``/healthz`` in every role, and
  runs in pytest behind ``TPUDASH_FDCHECK=1``.

- :mod:`tpudash.analysis.boundcheck` — ``python -m
  tpudash.analysis.boundcheck`` — untrusted-input exception contracts,
  both halves: an interprocedural static pass computing per-function
  exception *escape sets* over asynccheck's call graph, checked against
  a registry (``BOUNDARIES``) declaring every wire/segment/bundle/
  summary decoder's contract type — plus fan-in loops that call a
  boundary unguarded, ``except Exception`` wrapped around boundary
  calls, and wire-format id constants minted outside
  :mod:`tpudash.wireids` — and a runtime structure-aware wire fuzzer
  (``--fuzz``) that mutates real encoder output (seeded truncations,
  bit flips, length inflation, CRC-resealed edits, JSON shape swaps)
  and fails on any decode that escapes its contract, hangs, or blows
  the time budget.  Reproducible from the printed seed.

``python -m tpudash.analysis`` runs every static analyzer as one gate
(``--json`` for the machine-readable report; distinct exit codes per
analyzer — see :mod:`tpudash.analysis.cli`).  All of them ship with zero
suppressions in-tree beyond explicit, reasoned ``# tpulint: allow[rule]``
markers; the CI ``static-analysis`` job fails the build on any new
finding.
"""
