"""``python -m tpudash.analysis`` — every static analyzer, one entry point.

Runs tpulint (:mod:`tpudash.analysis.lint`), asynccheck
(:mod:`tpudash.analysis.asynccheck`), leakcheck
(:mod:`tpudash.analysis.leakcheck`) and boundcheck
(:mod:`tpudash.analysis.boundcheck`) over the same tree so CI and
editors consume one command instead of tracking the analyzer roster:

    python -m tpudash.analysis                 # analyze the package
    python -m tpudash.analysis path/ f.py      # analyze specific trees
    python -m tpudash.analysis --json          # machine-readable report
    python -m tpudash.analysis --rules         # list every rule

Exit codes are distinct bits so a consumer can tell WHICH gate failed
without parsing output:

    0   clean
    1   tpulint findings (bit)
    2   asynccheck findings (bit)
    8   leakcheck findings (bit)
    16  boundcheck findings (bit)
    4   usage/internal error (bad path, nothing to scan, registry import)

(a run with findings from several analyzers ORs the bits: tpulint +
leakcheck = 9, all four = 27)

``--json`` prints one object::

    {"version": 1, "clean": false,
     "counts": {"tpulint": 1, "asynccheck": 0, "leakcheck": 0,
                "boundcheck": 0},
     "findings": [{"analyzer": "tpulint", "rule": "wall-clock",
                   "file": "...", "line": 12, "message": "..."}]}

(boundcheck's wire fuzzer — ``python -m tpudash.analysis.boundcheck
--fuzz`` — plus racecheck, the loop-lag monitor and the resource census
are runtime checks; the latter three are wired through pytest — ``TPUDASH_RACECHECK=1`` /
``TPUDASH_LOOPCHECK=1`` / ``TPUDASH_FDCHECK=1`` — not part of this
static pass; see docs/DEVELOPMENT.md.)
"""

from __future__ import annotations

import json
import sys

from tpudash.analysis import asynccheck, boundcheck, leakcheck, lint

EXIT_CLEAN = 0
EXIT_LINT = 1
EXIT_ASYNC = 2
EXIT_USAGE = 4
EXIT_LEAK = 8
EXIT_BOUND = 16


def run_all(paths: "list[str]") -> dict:
    """All analyzers over ``paths``; returns the ``--json`` report shape
    (the CLI and tests share it so they can never disagree)."""
    declared = lint._declared_env()
    doc_text = lint._operations_doc_text()
    lint_findings = lint.lint_paths(
        paths, declared_env=declared, doc_text=doc_text
    )
    async_findings = asynccheck.check_paths(paths)
    leak_findings = leakcheck.check_paths(paths)
    bound_findings = boundcheck.check_paths(paths)
    findings = [
        {
            "analyzer": analyzer,
            "rule": f.rule,
            "file": f.path,
            "line": f.line,
            "message": f.message,
        }
        for analyzer, batch in (
            ("tpulint", lint_findings),
            ("asynccheck", async_findings),
            ("leakcheck", leak_findings),
            ("boundcheck", bound_findings),
        )
        for f in sorted(batch)
    ]
    return {
        "version": 1,
        "clean": not findings,
        "counts": {
            "tpulint": len(lint_findings),
            "asynccheck": len(async_findings),
            "leakcheck": len(leak_findings),
            "boundcheck": len(bound_findings),
        },
        "findings": findings,
    }


def main(argv: "list[str] | None" = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    if "--rules" in argv:
        for name, mod in (
            ("tpulint", lint),
            ("asynccheck", asynccheck),
            ("leakcheck", leakcheck),
            ("boundcheck", boundcheck),
        ):
            for rule in mod.ALL_RULES:
                print(f"{name}: {rule}: {mod.RULE_DOCS[rule]}")
        return EXIT_CLEAN
    paths, _err = lint.resolve_cli_paths(argv, "analysis")
    if paths is None:
        return EXIT_USAGE
    try:
        report = run_all(paths)
    except Exception as e:  # pragma: no cover - registry/import failure
        print(f"analysis: internal error: {e}", file=sys.stderr)
        return EXIT_USAGE
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        for f in report["findings"]:
            print(
                f"{f['file']}:{f['line']}: [{f['analyzer']}] {f['rule']}: "
                f"{f['message']}"
            )
        counts = report["counts"]
        if report["clean"]:
            print(
                "analysis: clean "
                "(tpulint + asynccheck + leakcheck + boundcheck)"
            )
        else:
            print(
                f"analysis: {counts['tpulint']} tpulint / "
                f"{counts['asynccheck']} asynccheck / "
                f"{counts['leakcheck']} leakcheck / "
                f"{counts['boundcheck']} boundcheck finding(s)",
                file=sys.stderr,
            )
    code = EXIT_CLEAN
    if report["counts"]["tpulint"]:
        code |= EXIT_LINT
    if report["counts"]["asynccheck"]:
        code |= EXIT_ASYNC
    if report["counts"]["leakcheck"]:
        code |= EXIT_LEAK
    if report["counts"]["boundcheck"]:
        code |= EXIT_BOUND
    return code
