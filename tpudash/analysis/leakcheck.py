"""leakcheck — interprocedural resource-lifetime analysis, static and runtime.

PR 16 made the stack a long-lived, multi-process, multi-host tree:
unix+TCP/TLS bus links with reconnect-forever loops, memfd seal rings,
follower tailers, executor hops, per-edge backlogs.  That is exactly the
shape where a single unclosed socket or orphaned thread *per reconnect*
compounds into an outage — a fleet monitor that leaks FDs under churn is
less reliable than what it watches, silently.  tpulint polices locks and
asynccheck polices the event loop; nothing audits resource *lifetimes*.
This module does, with the established two-half design:

Static rules (``python -m tpudash.analysis.leakcheck``)
-------------------------------------------------------
Built on asynccheck's module index and call-graph resolver: resource
*factories* (functions whose return value is a fresh resource) and
resource *closers* (functions that close a parameter) propagate across
call sites, so ``sock = self._handshake(...)`` is tracked like
``sock = socket.socket(...)`` and ``self._discard(sock)`` counts as a
close when ``_discard`` really closes its argument.

``unclosed-resource``
    A socket / ``open()`` file / memfd / ``SharedMemory`` / executor /
    ``aiohttp.ClientSession`` / ``mmap`` / TLS-wrapped socket is created
    outside ``with`` / ``try-finally`` / a registered cleanup
    (``contextlib.closing``, ``ExitStack.enter_context/callback``) and
    can escape the creating scope on some path — including the *error*
    paths of ``connect`` / handshake calls between creation and the
    close or ownership transfer.

``thread-no-join``
    A non-daemon ``threading.Thread`` is started without a ``join()``
    (or stop handle) reachable from shutdown: locally in the creating
    function, or — when retained on ``self`` — in some method of the
    owning class.  Non-daemon threads without a join owner turn every
    clean shutdown into a hang.

``task-no-cancel``
    A retained ``asyncio.create_task`` / ``ensure_future`` handle, or a
    ``call_later`` / ``call_at`` / ``threading.Timer`` timer, with no
    cancellation owner: the local handle is never awaited / cancelled /
    handed off, or the ``self``-retained handle has no method that both
    references it and cancels.  Extends asynccheck's ``unretained-task``
    (bare-expression spawns) to *lifecycle* — a retained-but-immortal
    task still outlives every shutdown path.

``finally-can-raise``
    A cleanup call (``close`` / ``shutdown`` / ``flush`` / ``unlink`` /
    ``terminate`` / ``wait_closed``) sits unguarded in a ``finally:``
    block: if it raises (closing a broken socket commonly does), it
    *replaces* the in-flight exception that triggered the cleanup.
    Wrap it in ``contextlib.suppress(OSError)`` or a local try/except.

Allow mechanism: identical to tpulint — ``# tpulint: allow[rule]
reason`` on the finding line, the line above, or a ``def`` header for
scope coverage.  Exit status 0 = clean; 1 = findings (``file:line:
rule: message``); 2 = usage error.

Runtime sanitizer (:class:`ResourceCensus`)
-------------------------------------------
Static rules cannot see dynamically-dispatched creation or refcount
keep-alives.  The census instruments the running process (refcounted
process-wide patches, mirroring racecheck's install model): ``socket``
construction, ``open()``, ``Thread.start()`` and ``loop.create_task()``
record a creation site; :func:`process_census` snapshots
``/proc/self/fd`` + ``threading.enumerate()`` + ``asyncio.all_tasks()``
and every server role (compose, worker, edge, follower) surfaces the
result as ``census`` — ``{fds, threads, tasks, high_water}`` — on
``/api/timings`` and ``/healthz``; the chaos drills assert zero net
growth between pre- and post-storm steady states.  The pytest suite
enables the census behind ``TPUDASH_FDCHECK=1`` (autouse fixture in
``tests/conftest.py``; tests that leak on purpose opt out with
``@pytest.mark.fdcheck_exempt``): any resource created during a test
and still alive at its end fails the test *with the creation site*.
"""

from __future__ import annotations

import ast
import gc
import os
import sys
import threading
import time
import weakref

from tpudash.analysis.asynccheck import (
    _FuncInfo,
    _ModuleInfo,
    _resolve,
    index_source,
)
from tpudash.analysis.lint import (
    Finding,
    _dotted,
    iter_py_files,
    resolve_cli_paths,
)

RULE_UNCLOSED = "unclosed-resource"
RULE_THREAD_JOIN = "thread-no-join"
RULE_TASK_CANCEL = "task-no-cancel"
RULE_FINALLY_RAISE = "finally-can-raise"

ALL_RULES = (
    RULE_UNCLOSED,
    RULE_THREAD_JOIN,
    RULE_TASK_CANCEL,
    RULE_FINALLY_RAISE,
)

RULE_DOCS = {
    RULE_UNCLOSED: (
        "sockets/files/memfds/SharedMemory/executors/client sessions must "
        "be created under with/try-finally/a registered cleanup, or every "
        "path from creation to close/ownership-transfer (including "
        "connect/handshake error paths) must be covered by a close"
    ),
    RULE_THREAD_JOIN: (
        "a non-daemon Thread that is start()ed needs a join()/stop handle "
        "reachable from shutdown (locally, or in a method of the class "
        "that retains it)"
    ),
    RULE_TASK_CANCEL: (
        "retained create_task/ensure_future handles and call_later/"
        "call_at/Timer timers need a cancellation owner — a method (or "
        "local path) that cancels/awaits them at shutdown"
    ),
    RULE_FINALLY_RAISE: (
        "cleanup calls in finally: blocks must not be able to raise over "
        "the in-flight exception — wrap close()/shutdown()/flush() in "
        "contextlib.suppress(...) or a local try/except"
    ),
}

#: call tails that create a resource needing an explicit close, → label
_RESOURCE_TAILS = {
    "socket": "socket",
    "socketpair": "socket pair",
    "create_connection": "socket",
    "wrap_socket": "TLS socket",
    "memfd_create": "memfd",
    "SharedMemory": "shared memory segment",
    "ThreadPoolExecutor": "executor",
    "ProcessPoolExecutor": "executor",
    "ClientSession": "client session",
}

#: <module>.open(...) roots that return a closeable handle (a bare
#: ``open(...)`` always does)
_OPEN_ROOTS = {"os", "io", "gzip", "bz2", "lzma", "mmap"}

#: method tails that end a resource's lifetime when called on its name
_CLEANUP_TAILS = {
    "close",
    "aclose",
    "shutdown",
    "terminate",
    "cancel",
    "detach",
    "release",
    "unlink",
}

#: call tails that register the resource with a managed-cleanup owner
_REGISTER_TAILS = {
    "closing",
    "aclosing",
    "enter_context",
    "enter_async_context",
    "push",
    "push_async_callback",
    "callback",
}

#: tails that cannot meaningfully fail between creation and close — they
#: do not count as "a call on the error path" (keeps the success-path
#: close rule about real hazards: connect, handshake, I/O, user calls)
_BENIGN_TAILS = {
    "setsockopt",
    "settimeout",
    "setblocking",
    "set_inheritable",
    "fileno",
    "getsockname",
    "getpeername",
    "debug",
    "info",
    "warning",
    "append",
    "get",
    "monotonic",
    "perf_counter",
}

#: cleanup tails in a ``finally:`` that can raise over the in-flight
#: exception (closing broken sockets/files raises OSError routinely)
_RAISING_CLEANUP_TAILS = {
    "close",
    "shutdown",
    "flush",
    "unlink",
    "remove",
    "terminate",
    "wait_closed",
}

_TASK_TAILS = {"create_task", "ensure_future"}
_TIMER_TAILS = {"call_later", "call_at", "Timer"}


def _is_cleanup_of(call: ast.Call, name: str) -> bool:
    """Does ``call`` end ``name``'s lifetime?  Two spellings: a cleanup
    method on the name (``name.close()``) and the raw-fd form
    (``os.close(name)`` / ``os.closerange(name, …)``)."""
    parts = _dotted(call.func)
    if parts is None:
        return False
    if len(parts) >= 2 and parts[0] == name and parts[-1] in _CLEANUP_TAILS:
        return True
    if (
        len(parts) == 2
        and parts[0] == "os"
        and parts[1] in ("close", "closerange")
        and call.args
        and isinstance(call.args[0], ast.Name)
        and call.args[0].id == name
    ):
        return True
    return False


def _call_ref(parts: "list[str]"):
    """Dotted call → asynccheck ``_resolve`` (kind, payload), or None."""
    if len(parts) == 1:
        return ("bare", parts[0])
    if parts[0] == "self" and len(parts) == 2:
        return ("self", parts[1])
    if len(parts) == 2:
        return ("attr", (parts[0], parts[1]))
    return None


def _syntactic_kind(parts: "list[str]") -> "str | None":
    """Resource label for a creation call spelled directly, else None."""
    tail = parts[-1]
    if tail in _RESOURCE_TAILS:
        return _RESOURCE_TAILS[tail]
    if tail == "open":
        if len(parts) == 1 or parts[0] in _OPEN_ROOTS:
            return "file handle"
        return None
    if tail == "mmap" and (len(parts) == 1 or parts[0] == "mmap"):
        return "mmap"
    return None


# ---------------------------------------------------------------------------
# Per-function fact collection (feeds the interprocedural fixpoint)
# ---------------------------------------------------------------------------


class _FnFacts:
    __slots__ = (
        "node",
        "mod",
        "fi",
        "class_name",
        "scope_lines",
        "params",
        "factory",
        "factory_kind",
        "closes",
        "return_calls",
        "returned_names",
        "name_calls",
    )

    def __init__(self, node, mod, fi, class_name, scope_lines):
        self.node = node
        self.mod = mod
        self.fi = fi
        self.class_name = class_name
        self.scope_lines = scope_lines
        self.params = [a.arg for a in _all_args(node.args)]
        self.factory = False
        self.factory_kind: "str | None" = None
        self.closes: set = set()  # param names this function closes
        self.return_calls: list = []  # (kind, payload) returned directly
        self.returned_names: set = set()
        self.name_calls: dict = {}  # local name → [(kind, payload)]


class _ClassFacts:
    """Per-class ownership evidence for self-retained threads/tasks:
    for each method, which ``self.<attr>`` names it touches and which
    method tails it calls — ``for t in self._tasks: t.cancel()`` makes
    the method an owner of ``_tasks`` for tail ``cancel``."""

    __slots__ = ("methods",)

    def __init__(self):
        self.methods: list = []  # (set of self attrs, set of call tails)

    def owns(self, attr: str, tails: "set[str]") -> bool:
        return any(
            attr in attrs and (call_tails & tails)
            for attrs, call_tails in self.methods
        )


def _all_args(args: ast.arguments):
    return [*args.posonlyargs, *args.args, *args.kwonlyargs]


def _func_body_nodes(fn_node):
    """Every AST node of the function body, nested defs excluded (they
    run on their own schedule and are analyzed as their own functions)."""
    out: list = []
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)
    return out


def _nested_def_names(fn_node) -> "set[str]":
    """Names referenced inside nested defs/lambdas of ``fn_node`` — a
    resource captured by a closure escapes the creating scope on the
    closure's schedule, so lifetime analysis gives it up (safe)."""
    names: set = set()
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
            continue
        if isinstance(node, ast.Lambda):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return names


def _collect_fn_facts(fn: _FnFacts) -> None:
    """Phase-A facts for the fixpoint: which calls this function returns,
    which locals those returns came from, which params it closes."""
    for node in _func_body_nodes(fn.node):
        if isinstance(node, ast.Return) and node.value is not None:
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if isinstance(value, ast.Call):
                parts = _dotted(value.func)
                if parts is not None:
                    fn.return_calls.append((parts, value))
            elif isinstance(value, ast.Name):
                fn.returned_names.add(value.id)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if isinstance(value, ast.Call):
                parts = _dotted(value.func)
                if parts is not None:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            fn.name_calls.setdefault(t.id, []).append(
                                (parts, value)
                            )
        elif isinstance(node, ast.Call):
            parts = _dotted(node.func)
            if (
                parts is not None
                and len(parts) == 2
                and parts[1] in _CLEANUP_TAILS
                and parts[0] in fn.params
            ):
                fn.closes.add(parts[0])
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id in fn.params:
                    fn.closes.add(ctx.id)


class _Facts:
    """Whole-tree view: asynccheck's module index for resolution plus
    leakcheck's per-function/per-class facts keyed by definition site."""

    def __init__(self):
        self.index: dict = {}  # module name → _ModuleInfo
        self.fns: dict = {}  # (path, lineno) → _FnFacts
        self.classes: dict = {}  # (path, class name) → _ClassFacts

    def facts_for(self, fi: "_FuncInfo | None") -> "_FnFacts | None":
        if fi is None:
            return None
        return self.fns.get((fi.path, fi.lineno))

    def resolve_call(
        self, fn: _FnFacts, parts: "list[str]"
    ) -> "_FnFacts | None":
        ref = _call_ref(parts)
        if ref is None or fn.fi is None:
            return None
        mod = self.index.get(fn.mod.name, fn.mod)
        target = _resolve(self.index, mod, fn.fi, ref[0], ref[1])
        return self.facts_for(target)

    def creation_kind(
        self, fn: _FnFacts, parts: "list[str]"
    ) -> "str | None":
        """Resource label for a call: spelled directly, or resolving to
        a factory function (the interprocedural half)."""
        kind = _syntactic_kind(parts)
        if kind is not None:
            return kind
        callee = self.resolve_call(fn, parts)
        if callee is not None and callee.factory:
            return callee.factory_kind or "resource"
        return None

    def call_closes_arg(
        self, fn: _FnFacts, call: ast.Call, arg_node
    ) -> bool:
        """True when ``call`` resolves to a function that closes the
        parameter ``arg_node`` is bound to."""
        parts = _dotted(call.func)
        if parts is None:
            return False
        callee = self.resolve_call(fn, parts)
        if callee is None or not callee.closes:
            return False
        # positional binding; methods resolved via self drop the self slot
        params = callee.params
        if params and params[0] == "self":
            params = params[1:]
        for i, a in enumerate(call.args):
            if a is arg_node:
                if i < len(params) and params[i] in callee.closes:
                    return True
        for kw in call.keywords:
            if kw.value is arg_node and kw.arg in callee.closes:
                return True
        return False


def _fixpoint(facts: _Facts) -> None:
    """Propagate factory-ness (returns a fresh resource) and closer-ness
    (closes a parameter) through resolved calls until stable."""
    changed = True
    while changed:
        changed = False
        for fn in facts.fns.values():
            if fn.factory:
                continue
            kind = None
            for parts, _call in fn.return_calls:
                kind = facts.creation_kind(fn, parts)
                if kind is not None:
                    break
            if kind is None:
                for name in fn.returned_names:
                    for parts, _call in fn.name_calls.get(name, ()):
                        kind = facts.creation_kind(fn, parts)
                        if kind is not None:
                            break
                    if kind is not None:
                        break
            if kind is not None:
                fn.factory = True
                fn.factory_kind = kind
                changed = True


# ---------------------------------------------------------------------------
# Rule analysis proper
# ---------------------------------------------------------------------------


class _FnAnalysis:
    """One function's lifetime verdicts.  Findings append to ``out``."""

    def __init__(self, fn: _FnFacts, facts: _Facts, out: "list[Finding]"):
        self.fn = fn
        self.facts = facts
        self.out = out
        self.mod = fn.mod
        self.body = _func_body_nodes(fn.node)
        self.closure_names = _nested_def_names(fn.node)
        # parent links inside this function (nested defs excluded)
        self.parents: dict = {}
        for node in self.body:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        for child in ast.iter_child_nodes(self.fn.node):
            self.parents.setdefault(child, self.fn.node)

    # -- shared helpers ------------------------------------------------------
    def _flag(self, rule: str, line: int, message: str) -> None:
        if self.mod.allowed(rule, line, self.fn.scope_lines):
            return
        self.out.append(Finding(self.mod.path, line, rule, message))

    def _ancestors(self, node):
        seen = set()
        while node is not None and id(node) not in seen:
            seen.add(id(node))
            yield node
            node = self.parents.get(node)

    def _cleans_name(self, stmts, name: str) -> bool:
        """Does this statement list close/cancel ``name``?"""
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and _is_cleanup_of(node, name):
                    return True
        return False

    def _guarded(self, node, name: str) -> bool:
        """Is ``node`` inside a try whose finally/handlers close ``name``
        (so an exception at ``node`` cannot leak it)?"""
        for anc in self._ancestors(node):
            if isinstance(anc, ast.Try):
                if self._cleans_name(anc.finalbody, name):
                    return True
                for handler in anc.handlers:
                    if self._cleans_name(handler.body, name):
                        return True
        return False

    # -- unclosed-resource ---------------------------------------------------
    def check_resources(self) -> None:
        for node in self.body:
            if not isinstance(node, ast.Call):
                continue
            parts = _dotted(node.func)
            if parts is None:
                continue
            kind = self.facts.creation_kind(self.fn, parts)
            if kind is None:
                continue
            self._check_one_creation(node, parts, kind)

    def _check_one_creation(self, call, parts, kind) -> None:
        label = ".".join(parts)
        parent = self.parents.get(call)
        if isinstance(parent, ast.Await):
            call = parent
            parent = self.parents.get(parent)
        if isinstance(parent, ast.withitem):
            return  # with socket.socket(...) as s:
        if isinstance(parent, ast.Call):
            wrapper = _dotted(parent.func)
            if wrapper is not None and wrapper[-1] in _REGISTER_TAILS:
                return  # closing(...) / stack.enter_context(...)
            if wrapper is not None and _syntactic_kind(wrapper) is not None:
                return  # wrap_socket(socket(...)): outer creation owns it
            # handed straight to a callee: close there, or ownership moved
            return
        if isinstance(parent, ast.Return):
            return  # factory: caller owns it (tracked at the call site)
        if isinstance(parent, ast.Attribute):
            # chained call on the fresh resource with the handle dropped:
            # open(p).read() leaks the file on CPython refcount grace only
            grand = self.parents.get(parent)
            if isinstance(grand, ast.Call):
                tail = parent.attr
                if tail in _CLEANUP_TAILS:
                    return
                self._flag(
                    RULE_UNCLOSED,
                    call.lineno,
                    f"{kind} from {label}(...) is used and dropped in one "
                    "expression — nothing can ever close it; bind it under "
                    "`with` or close it explicitly",
                )
            return
        if isinstance(parent, ast.Expr):
            self._flag(
                RULE_UNCLOSED,
                call.lineno,
                f"{kind} from {label}(...) is created and discarded — the "
                "handle is unreachable and stays open until interpreter "
                "exit; bind it under `with` or close it",
            )
            return
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            targets = (
                parent.targets
                if isinstance(parent, ast.Assign)
                else [parent.target]
            )
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                self._check_named_resource(
                    targets[0].id, call, parts, kind, parent
                )
            # self.attr / subscript / tuple targets: object-lifetime
            # ownership — the retaining object's close discipline owns it
            return
        # collection element, yield, comparison, … : ownership escapes to
        # a structure we cannot see; give the benefit of the doubt

    def _check_named_resource(self, name, call, parts, kind, assign):
        if name in self.closure_names:
            return  # captured by a nested def: closure owns the lifetime
        label = ".".join(parts)
        created = call.lineno
        cleanup_sites: list = []  # (node, in_finally, in_except)
        registered = False
        transfer_line: "int | None" = None  # return/yield/re-home
        arg_transfer_line: "int | None" = None  # passed to a callee
        with_managed = False
        for node in self.body:
            line = getattr(node, "lineno", 0)
            if line < created:
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name) and ctx.id == name:
                        with_managed = True
            elif isinstance(node, ast.Call):
                cparts = _dotted(node.func)
                if cparts is None:
                    # dynamically-computed callee taking the name: treat
                    # as ownership transfer below via the generic scan
                    pass
                elif _is_cleanup_of(node, name):
                    in_finally = in_except = False
                    for anc in self._ancestors(node):
                        p = self.parents.get(anc)
                        if isinstance(p, ast.Try) and anc in getattr(
                            p, "finalbody", ()
                        ):
                            in_finally = True
                        if isinstance(anc, ast.ExceptHandler):
                            in_except = True
                    cleanup_sites.append((node, in_finally, in_except))
                elif cparts[-1] in _REGISTER_TAILS and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in node.args
                ):
                    registered = True
                elif any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in (*node.args, *(kw.value for kw in node.keywords))
                ):
                    arg = next(
                        a
                        for a in (
                            *node.args,
                            *(kw.value for kw in node.keywords),
                        )
                        if isinstance(a, ast.Name) and a.id == name
                    )
                    if self.facts.call_closes_arg(self.fn, node, arg):
                        in_finally = any(
                            isinstance(self.parents.get(anc), ast.Try)
                            and anc
                            in getattr(self.parents.get(anc), "finalbody", ())
                            for anc in self._ancestors(node)
                        )
                        cleanup_sites.append((node, in_finally, False))
                    elif arg_transfer_line is None:
                        arg_transfer_line = line
            elif isinstance(node, ast.Return) and node.value is not None:
                if any(
                    isinstance(sub, ast.Name) and sub.id == name
                    for sub in ast.walk(node.value)
                ):
                    if transfer_line is None:
                        transfer_line = line
            elif isinstance(node, (ast.Assign, ast.AnnAssign)) and node is not assign:
                value = node.value
                if isinstance(value, ast.Call) or (
                    isinstance(value, ast.Await)
                    and isinstance(value.value, ast.Call)
                ):
                    # `x = f(name)` re-homes nothing by itself — the Call
                    # node scan decides (close / register / arg transfer)
                    continue
                if value is not None and any(
                    isinstance(sub, ast.Name) and sub.id == name
                    for sub in ast.walk(value)
                ):
                    # aliased / stored on self / into a structure
                    if transfer_line is None:
                        transfer_line = line
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                if node.value is not None and any(
                    isinstance(sub, ast.Name) and sub.id == name
                    for sub in ast.walk(node.value)
                ):
                    if transfer_line is None:
                        transfer_line = line
        if with_managed or registered:
            return
        if any(in_finally for _n, in_finally, _e in cleanup_sites):
            return
        if not cleanup_sites and transfer_line is None:
            # only a plain call ever sees the name: assume the callee
            # takes ownership (closes or retains it)
            transfer_line = arg_transfer_line
        risky = self._risky_between(
            created,
            min(
                [s[0].lineno for s in cleanup_sites]
                + ([transfer_line] if transfer_line is not None else []),
                default=None,
            ),
            name,
            skip={id(call)} | {id(s[0]) for s in cleanup_sites},
            creation=call,
        )
        if cleanup_sites:
            if all(in_except for _n, _f, in_except in cleanup_sites) and (
                transfer_line is None and arg_transfer_line is None
            ):
                self._flag(
                    RULE_UNCLOSED,
                    created,
                    f"{kind} `{name}` from {label}(...) is closed only in "
                    "an except handler — the success path never closes it "
                    "and it escapes the scope open",
                )
                return
            if risky is not None:
                self._flag(
                    RULE_UNCLOSED,
                    created,
                    f"{kind} `{name}` from {label}(...) is closed only on "
                    f"the success path — if line {risky} raises first, "
                    "the handle escapes open; close it in a finally: or "
                    "use `with`",
                )
            return
        if transfer_line is not None:
            if risky is not None:
                self._flag(
                    RULE_UNCLOSED,
                    created,
                    f"{kind} `{name}` from {label}(...) leaks on the error "
                    f"path: line {risky} can raise before ownership moves "
                    f"at line {transfer_line}; close `{name}` in an "
                    "except/finally covering that window",
                )
            return
        self._flag(
            RULE_UNCLOSED,
            created,
            f"{kind} `{name}` from {label}(...) is never closed and never "
            "escapes this scope — it leaks on every path; use `with` or "
            "close it in a finally:",
        )

    def _risky_between(self, start, end, name, skip, creation) -> "int | None":
        """First line in (start, end) whose call/await can raise before
        the resource is safe — the error-path escape window.  ``end`` of
        None means "to the end of the function"."""
        creation_handlers = {
            id(anc)
            for anc in self._ancestors(creation)
            if isinstance(anc, ast.ExceptHandler)
        }
        for node in self.body:
            line = getattr(node, "lineno", 0)
            if line <= start:
                continue
            if end is not None and line >= end:
                continue
            if id(node) in skip:
                continue
            if not isinstance(node, (ast.Await, ast.Call)):
                continue
            # a statement inside an except handler that does NOT contain
            # the creation runs only when the creation's own try body
            # raised — it is not on the creation's success path
            if any(
                isinstance(anc, ast.ExceptHandler)
                and id(anc) not in creation_handlers
                for anc in self._ancestors(node)
            ):
                continue
            if isinstance(node, ast.Await):
                if not self._guarded(node, name):
                    return line
                continue
            parts = _dotted(node.func)
            if parts is not None and (
                parts[-1] in _BENIGN_TAILS
                or parts[-1] in _CLEANUP_TAILS
                or parts[-1] in _REGISTER_TAILS
                or parts[-1] == "suppress"
            ):
                continue
            if _is_cleanup_of(node, name):
                continue
            if not self._guarded(node, name):
                return line
        return None

    # -- thread-no-join ------------------------------------------------------
    def check_threads(self) -> None:
        for node in self.body:
            if not isinstance(node, ast.Call):
                continue
            parts = _dotted(node.func)
            if parts is None or parts[-1] != "Thread":
                continue
            if _kw_is_true(node, "daemon"):
                continue
            self._check_one_thread(node, parts)

    def _check_one_thread(self, call, parts) -> None:
        label = ".".join(parts)
        parent = self.parents.get(call)
        if isinstance(parent, ast.Attribute) and parent.attr == "start":
            # Thread(...).start(): no handle exists to ever join
            self._flag(
                RULE_THREAD_JOIN,
                call.lineno,
                f"non-daemon {label}(...).start() drops the only handle — "
                "nothing can join it at shutdown; retain it (and join) or "
                "pass daemon=True",
            )
            return
        name = attr = None
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = (
                parent.targets
                if isinstance(parent, ast.Assign)
                else [parent.target]
            )
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                name = targets[0].id
            elif (
                len(targets) == 1
                and isinstance(targets[0], ast.Attribute)
                and isinstance(targets[0].value, ast.Name)
                and targets[0].value.id == "self"
            ):
                attr = targets[0].attr
        else:
            return  # returned / collected: caller owns the join
        if name is not None:
            started = joined = daemonized = transferred = False
            for node in self.body:
                if isinstance(node, ast.Call):
                    cparts = _dotted(node.func)
                    if cparts is not None and len(cparts) >= 2 and cparts[0] == name:
                        if cparts[-1] == "start":
                            started = True
                        if cparts[-1] == "join":
                            joined = True
                    elif cparts is not None and any(
                        isinstance(a, ast.Name) and a.id == name
                        for a in (
                            *node.args,
                            *(kw.value for kw in node.keywords),
                        )
                    ):
                        transferred = True
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and t.attr == "daemon"
                            and isinstance(t.value, ast.Name)
                            and t.value.id == name
                        ):
                            daemonized = _const_true(node.value)
                        if isinstance(t, ast.Attribute) and any(
                            isinstance(sub, ast.Name) and sub.id == name
                            for sub in ast.walk(node.value)
                        ):
                            transferred = True
                elif isinstance(node, ast.Return) and node.value is not None:
                    if any(
                        isinstance(sub, ast.Name) and sub.id == name
                        for sub in ast.walk(node.value)
                    ):
                        transferred = True
            if name in self.closure_names:
                transferred = True
            if started and not (joined or daemonized or transferred):
                self._flag(
                    RULE_THREAD_JOIN,
                    call.lineno,
                    f"non-daemon thread `{name}` is started but never "
                    "joined and never handed off — shutdown cannot reach "
                    "it; join it, hand it to an owner, or pass daemon=True",
                )
            return
        if attr is not None:
            cls = self.facts.classes.get((self.mod.path, self.fn.class_name))
            if cls is None or not cls.owns(attr, {"join"}):
                self._flag(
                    RULE_THREAD_JOIN,
                    call.lineno,
                    f"non-daemon thread on self.{attr} has no join owner — "
                    f"no method of {self.fn.class_name or 'this class'} "
                    f"references self.{attr} and calls join(); add one to "
                    "the shutdown path or pass daemon=True",
                )

    # -- task-no-cancel ------------------------------------------------------
    def check_tasks(self) -> None:
        for node in self.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                continue  # await create_task(...) completes inline
            if not isinstance(value, ast.Call):
                continue
            parts = _dotted(value.func)
            if parts is None:
                continue
            if parts[-1] in _TASK_TAILS:
                what, verb = "task", "cancels"
            elif parts[-1] in _TIMER_TAILS:
                what, verb = "timer", "cancels"
            else:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if len(targets) != 1:
                continue
            target = targets[0]
            if isinstance(target, ast.Name):
                self._check_local_task(target.id, value, parts, what)
            elif (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                self._check_attr_task(target.attr, value, parts, what, verb)

    def _check_local_task(self, name, call, parts, what) -> None:
        if name in self.closure_names:
            return
        label = ".".join(parts)
        for node in self.body:
            if isinstance(node, ast.Call):
                cparts = _dotted(node.func)
                if cparts is not None and len(cparts) >= 2 and cparts[0] == name:
                    if cparts[-1] in ("cancel", "add_done_callback", "result"):
                        return
                if cparts is not None and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in (*node.args, *(kw.value for kw in node.keywords))
                ):
                    return  # gathered / waited / handed to an owner
            elif isinstance(node, ast.Await):
                if any(
                    isinstance(sub, ast.Name) and sub.id == name
                    for sub in ast.walk(node.value)
                ):
                    return
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                if (
                    value is not None
                    and not (isinstance(value, ast.Call) and value is call)
                    and any(
                        isinstance(sub, ast.Name) and sub.id == name
                        for sub in ast.walk(value)
                    )
                ):
                    return  # re-homed (self.x = t, dict[k] = t, …)
            elif isinstance(node, ast.Return) and node.value is not None:
                if any(
                    isinstance(sub, ast.Name) and sub.id == name
                    for sub in ast.walk(node.value)
                ):
                    return
        self._flag(
            RULE_TASK_CANCEL,
            call.lineno,
            f"{what} `{name}` from {label}(...) is retained here but "
            "never awaited, cancelled, or handed to an owner — at scope "
            "exit it runs unsupervised exactly like an unretained spawn",
        )

    def _check_attr_task(self, attr, call, parts, what, verb) -> None:
        label = ".".join(parts)
        cls = self.facts.classes.get((self.mod.path, self.fn.class_name))
        if cls is not None and cls.owns(attr, {"cancel", "join"}):
            return
        self._flag(
            RULE_TASK_CANCEL,
            call.lineno,
            f"long-lived {what} on self.{attr} ({label}) has no "
            f"cancellation owner — no method of "
            f"{self.fn.class_name or 'this class'} references "
            f"self.{attr} and {verb}; wire it into the shutdown path",
        )


# -- finally-can-raise (module-wide, no function context needed) -------------


def _check_finally(tree, mod: _ModuleInfo, out: "list[Finding]") -> None:
    # scope lines for allow markers: enclosing def headers per node
    def walk(node, scopes, suppressed):
        if isinstance(node, ast.Try) and node.finalbody and not suppressed:
            for stmt in node.finalbody:
                _scan_final_stmt(stmt, mod, scopes, out)
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
            isinstance(item.context_expr, ast.Call)
            and (_dotted(item.context_expr.func) or [""])[-1] == "suppress"
            for item in node.items
        ):
            # everything under `with contextlib.suppress(...)` already
            # swallows what its cleanup calls could raise
            suppressed = True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, scopes + [child.lineno], suppressed)
            else:
                walk(child, scopes, suppressed)

    walk(tree, [], False)


def _scan_final_stmt(stmt, mod, scopes, out, guarded=False) -> None:
    """Flag unguarded raising-cleanup calls in a finally statement.
    Guards: a nested try with handlers, or `with contextlib.suppress`."""
    if isinstance(stmt, ast.Try) and stmt.handlers:
        for sub in (*stmt.body, *stmt.orelse, *stmt.finalbody):
            _scan_final_stmt(sub, mod, scopes, out, guarded=True)
        for handler in stmt.handlers:
            for sub in handler.body:
                _scan_final_stmt(sub, mod, scopes, out, guarded=guarded)
        return
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        suppressed = guarded or any(
            isinstance(item.context_expr, ast.Call)
            and (_dotted(item.context_expr.func) or [""])[-1] == "suppress"
            for item in stmt.items
        )
        for sub in stmt.body:
            _scan_final_stmt(sub, mod, scopes, out, guarded=suppressed)
        return
    if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
        for sub in (*stmt.body, *stmt.orelse):
            _scan_final_stmt(sub, mod, scopes, out, guarded=guarded)
        return
    if guarded:
        return
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            parts = _dotted(node.func)
            if parts is None or len(parts) < 2:
                continue
            if parts[-1] not in _RAISING_CLEANUP_TAILS:
                continue
            if mod.allowed(RULE_FINALLY_RAISE, node.lineno, scopes):
                continue
            out.append(
                Finding(
                    mod.path,
                    node.lineno,
                    RULE_FINALLY_RAISE,
                    f"{'.'.join(parts)}(...) in a finally: block can raise "
                    "(closing broken handles raises OSError) and would "
                    "REPLACE the in-flight exception that triggered this "
                    "cleanup — wrap it in contextlib.suppress(OSError) or "
                    "a local try/except",
                )
            )


def _kw_is_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name:
            return _const_true(kw.value)
    return False


def _const_true(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def _build_facts(sources: "list[tuple[str, str]]") -> "tuple[_Facts, list]":
    """Parse + index every (source, path); returns facts and parse
    findings.  Each module is indexed twice: asynccheck's table for call
    resolution, leakcheck's own AST walk for lifetime structure."""
    facts = _Facts()
    findings: list = []
    trees: list = []
    for source, path in sources:
        mod = index_source(source, path)
        if isinstance(mod, Finding):
            findings.append(mod)
            continue
        facts.index[mod.name] = mod
        tree = ast.parse(source, filename=path)
        trees.append((tree, mod))
        fi_by_site = {(f.path, f.lineno): f for f in mod.funcs}

        def collect(node, class_name, scopes, mod=mod, fi_by_site=fi_by_site):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    key = (mod.path, child.name)
                    facts.classes.setdefault(key, _ClassFacts())
                    collect(child, child.name, scopes)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fn = _FnFacts(
                        child,
                        mod,
                        fi_by_site.get((mod.path, child.lineno)),
                        class_name,
                        scopes + [child.lineno],
                    )
                    _collect_fn_facts(fn)
                    facts.fns[(mod.path, child.lineno)] = fn
                    if class_name is not None and not scopes:
                        cls = facts.classes.setdefault(
                            (mod.path, class_name), _ClassFacts()
                        )
                        attrs: set = set()
                        tails: set = set()
                        for sub in ast.walk(child):
                            if (
                                isinstance(sub, ast.Attribute)
                                and isinstance(sub.value, ast.Name)
                                and sub.value.id == "self"
                            ):
                                attrs.add(sub.attr)
                            if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute
                            ):
                                tails.add(sub.func.attr)
                        cls.methods.append((attrs, tails))
                    collect(child, class_name, scopes + [child.lineno])
                else:
                    collect(child, class_name, scopes)

        collect(tree, None, [])
    _fixpoint(facts)
    # rule passes need the fixpoint done first
    for tree, mod in trees:
        _check_finally(tree, mod, findings)
    for fn in facts.fns.values():
        analysis = _FnAnalysis(fn, facts, findings)
        analysis.check_resources()
        analysis.check_threads()
        analysis.check_tasks()
    return facts, findings


def check_source(source: str, path: str = "<string>") -> "list[Finding]":
    """Single-source entry point (unit tests)."""
    _facts, findings = _build_facts([(source, path)])
    return sorted(findings)


def check_paths(paths: "list[str]") -> "list[Finding]":
    sources: list = []
    findings: list = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                sources.append((f.read(), path))
        except OSError as e:
            findings.append(Finding(path, 1, "io", f"cannot read: {e}"))
    _facts, batch = _build_facts(sources)
    findings.extend(batch)
    return sorted(findings)


def main(argv: "list[str] | None" = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--rules" in argv:
        for rule in ALL_RULES:
            print(f"{rule}: {RULE_DOCS[rule]}")
        return 0
    paths, err = resolve_cli_paths(argv, "leakcheck")
    if paths is None:
        return err
    findings = check_paths(paths)
    for finding in findings:
        print(finding)
    if findings:
        print(
            f"leakcheck: {len(findings)} finding"
            f"{'s' if len(findings) != 1 else ''} "
            f"across {len(set(f.path for f in findings))} file(s)",
            file=sys.stderr,
        )
        return 1
    print("leakcheck: clean")
    return 0


# ---------------------------------------------------------------------------
# Runtime resource census
# ---------------------------------------------------------------------------

_PATCH_LOCK = threading.Lock()
#: immutable snapshot, REPLACED (never mutated) under _PATCH_LOCK so the
#: creation wrappers can read it lock-free from any thread (racecheck's
#: install model)
_ACTIVE: "tuple[ResourceCensus, ...]" = ()
_ORIG: dict = {}

#: process-lifetime maxima behind the ``high_water`` census key — every
#: role's /healthz and /api/timings read the same counters, so the chaos
#: drills can compare pre/post-storm steady states per process
_HIGH_WATER = {"fds": 0, "threads": 0, "tasks": 0}

#: frames from these files are machinery, not the creation site
_INTERNAL_FILES = (
    "leakcheck.py",
    "socket.py",
    "ssl.py",
    "threading.py",
    "tasks.py",
    "base_events.py",
    "selector_events.py",
    "unix_events.py",
    "streams.py",
)

#: worker-pool threads are reclaimed by their executor's atexit join —
#: an idle pool worker outliving a test window is by design, not a leak
_POOL_THREAD_PREFIXES = ("ThreadPoolExecutor", "asyncio_")


def raw_counts() -> dict:
    """Point-in-time ``{fds, threads, tasks}`` for THIS process."""
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:  # non-procfs platform: census still counts the rest
        fds = -1
    threads = threading.active_count()
    try:
        import asyncio

        tasks = len(asyncio.all_tasks())
    except RuntimeError:  # no running loop on this thread
        tasks = 0
    return {"fds": fds, "threads": threads, "tasks": tasks}


def process_census() -> dict:
    """The census document every role surfaces on /api/timings and
    /healthz: current counts plus process-lifetime high-water marks."""
    counts = raw_counts()
    for key, value in counts.items():
        if value > _HIGH_WATER[key]:
            _HIGH_WATER[key] = value
    counts["high_water"] = dict(_HIGH_WATER)
    return counts


async def warm_default_executor() -> None:
    """Spawn the running loop's default executor to its full thread
    complement.  Executor threads are created lazily and never exit, so
    a process that takes its first census before its first burst of
    executor work reports the burst's warmup as thread growth forever
    after.  Serving processes call this at startup: the thread footprint
    becomes deterministic, and census comparisons (chaos drills, the
    fd-growth runbook in docs/OPERATIONS.md) compare steady state
    against steady state instead of cold start against warm."""
    import asyncio

    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, lambda: None)  # create the pool
    pool = getattr(loop, "_default_executor", None)
    n = getattr(pool, "_max_workers", 0) or 0
    if not n:
        return
    gate = threading.Event()
    # every task blocks, so each submit finds no idle worker and
    # ThreadPoolExecutor spawns a new thread, up to max_workers
    futures = [loop.run_in_executor(None, gate.wait, 10.0) for _ in range(n)]
    gate.set()
    await asyncio.gather(*futures)


def _creation_site(limit: int = 5) -> str:
    """Nearest non-internal frames at a creation, cheap (no source
    reads): ``file:line in func <- caller:line in func …``."""
    frame = sys._getframe(2)
    parts: list = []
    while frame is not None and len(parts) < limit:
        fn = frame.f_code.co_filename
        # exact-basename match: a suffix test would also hide user files
        # that merely END with an internal name (tests/test_leakcheck.py
        # ends with leakcheck.py) and misattribute their creations
        if os.path.basename(fn) not in _INTERNAL_FILES:
            parts.append(
                f"{fn}:{frame.f_lineno} in {frame.f_code.co_name}"
            )
        frame = frame.f_back
    return " <- ".join(parts) if parts else "<unknown>"


def _note(kind: str, obj) -> None:
    active = _ACTIVE
    if not active:
        return
    try:
        ref = weakref.ref(obj)
    except TypeError:
        return
    site = _creation_site()
    for census in active:
        census._record(kind, ref, site)


def _patched_socket_init(self, *args, **kwargs):
    _ORIG["socket_init"](self, *args, **kwargs)
    _note("socket", self)


def _patched_open(*args, **kwargs):
    handle = _ORIG["open"](*args, **kwargs)
    _note("file", handle)
    return handle


def _patched_thread_start(self):
    _note("thread", self)
    return _ORIG["thread_start"](self)


def _patched_create_task(self, coro, **kwargs):
    task = _ORIG["create_task"](self, coro, **kwargs)
    _note("task", task)
    return task


def _patch() -> None:
    import builtins
    import socket as socket_mod
    from asyncio import base_events

    _ORIG["socket_init"] = socket_mod.socket.__init__
    _ORIG["open"] = builtins.open
    _ORIG["thread_start"] = threading.Thread.start
    _ORIG["create_task"] = base_events.BaseEventLoop.create_task
    socket_mod.socket.__init__ = _patched_socket_init
    builtins.open = _patched_open
    threading.Thread.start = _patched_thread_start
    base_events.BaseEventLoop.create_task = _patched_create_task


def _unpatch() -> None:
    import builtins
    import socket as socket_mod
    from asyncio import base_events

    socket_mod.socket.__init__ = _ORIG["socket_init"]
    builtins.open = _ORIG["open"]
    threading.Thread.start = _ORIG["thread_start"]
    base_events.BaseEventLoop.create_task = _ORIG["create_task"]


class ResourceCensus:
    """Runtime FD/thread/task leak sanitizer (see module docstring).

    Install/uninstall mirror :class:`~tpudash.analysis.racecheck.RaceCheck`:
    a refcounted process-wide patch window; every socket/file/thread/task
    created inside the window is recorded with its creation site, and
    :meth:`assert_clean` fails if any of them is still alive once the
    window's work should have wound down — naming the site, which is the
    difference between "fds grew" and a fixable bug report."""

    def __init__(self, grace: float = 2.0):
        #: seconds assert_clean waits for in-flight teardown (loop
        #: close, thread joins, GC of just-dropped handles) to finish
        self.grace = grace
        self.baseline: "dict | None" = None
        self._entries: list = []  # (kind, weakref, site)
        self._lock = threading.Lock()
        self._installed = False

    # -- install / uninstall -------------------------------------------------
    def install(self) -> "ResourceCensus":
        global _ACTIVE
        if self._installed:
            return self
        with _PATCH_LOCK:
            if not _ACTIVE:
                _patch()
            _ACTIVE = (*_ACTIVE, self)
        self._installed = True
        self.baseline = raw_counts()
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if not self._installed:
            return
        with _PATCH_LOCK:
            _ACTIVE = tuple(c for c in _ACTIVE if c is not self)
            if not _ACTIVE:
                _unpatch()
        self._installed = False

    def __enter__(self) -> "ResourceCensus":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- recording (creation wrappers, any thread) ---------------------------
    def _record(self, kind: str, ref, site: str) -> None:
        with self._lock:
            self._entries.append((kind, ref, site))

    # -- reporting ------------------------------------------------------------
    def _alive(self, kind: str, obj) -> bool:
        if obj is None:
            return False
        if kind == "socket":
            try:
                return obj.fileno() >= 0
            except OSError:
                return False
        if kind == "file":
            return not getattr(obj, "closed", True)
        if kind == "thread":
            if obj.name.startswith(_POOL_THREAD_PREFIXES):
                return False
            return obj.is_alive()
        if kind == "task":
            if obj.done():
                return False
            try:
                return not obj.get_loop().is_closed()
            except RuntimeError:
                return False
        return False

    def leaked(self) -> "list[dict]":
        """Tracked resources created in the window and still alive —
        each with the creation site that made it."""
        out: list = []
        with self._lock:
            entries = list(self._entries)
        for kind, ref, site in entries:
            obj = ref()
            if self._alive(kind, obj):
                out.append({"kind": kind, "site": site, "obj": repr(obj)})
        return out

    def snapshot(self) -> dict:
        """Census + growth vs the install-time baseline + live tracked
        counts, for drills and debugging."""
        counts = process_census()
        base = self.baseline or counts
        counts["delta"] = {
            k: counts[k] - base[k] for k in ("fds", "threads", "tasks")
        }
        tracked: dict = {}
        for entry in self.leaked():
            tracked[entry["kind"]] = tracked.get(entry["kind"], 0) + 1
        counts["tracked_live"] = tracked
        return counts

    def assert_clean(self) -> None:
        """Raise AssertionError naming every leaked resource and its
        creation site.  Retries under ``grace`` first: loop shutdown,
        thread joins, and GC of just-dropped handles are legitimate
        in-flight teardown, not leaks."""
        deadline = time.monotonic() + max(self.grace, 0.0)
        while True:
            gc.collect()
            bad = self.leaked()
            if not bad:
                return
            if time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        counts = self.snapshot()
        lines = [
            f"fdcheck: {len(bad)} resource(s) created in this window are "
            f"still alive (census {counts['fds']} fds / "
            f"{counts['threads']} threads / {counts['tasks']} tasks, "
            f"delta {counts['delta']}):"
        ]
        for entry in bad[:10]:
            lines.append(f"  leaked {entry['kind']}: {entry['obj']}")
            lines.append(f"    created at {entry['site']}")
        if len(bad) > 10:
            lines.append(f"  … and {len(bad) - 10} more")
        raise AssertionError("\n".join(lines))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
