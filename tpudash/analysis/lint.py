"""tpulint — AST enforcement of tpudash's project invariants.

Generic linters catch generic bugs; these rules encode decisions THIS
project made and then nearly lost to drift (each rule names the incident
class that motivated it):

``wall-clock``
    No ``time.time()`` calls without an explicit allow marker.  Deadline,
    backoff, cadence, and breaker arithmetic must use ``time.monotonic()``
    — an NTP step during an outage must not instantly expire (or freeze)
    every breaker cooldown and retry budget.  Wall-clock is legitimate
    exactly where the value *is* a timestamp (Prometheus range bounds,
    recorder ``ts``, silence expiries shown to operators); those sites
    carry ``# tpulint: allow[wall-clock] <reason>`` so the intent is
    auditable in-tree.

``env-read``
    No reads of ``TPUDASH_*`` environment variables outside
    ``tpudash/config.py``.  All configuration flows through the registry
    (``Config`` / ``_ENV_MAP`` / ``_EXTRA_ENV``) so one file answers
    "what knobs exist" and the docs check below can hold.

``blocking-under-lock``
    No blocking calls — ``requests.*``, ``time.sleep``, file I/O,
    sockets, subprocesses — while a ``threading.Lock``/``RLock`` is held
    (lexically inside ``with <...lock...>:``, or inside a ``*_locked``
    function, the project's naming convention for "caller holds the
    lock").  A webhook POST under the publish lock stalls every
    dashboard route for ``http_timeout`` seconds.

``broad-except``
    No bare ``except:`` and no ``except BaseException:`` that fails to
    re-raise.  Source fetch paths swallowing ``BaseException`` eat
    ``KeyboardInterrupt``/``SystemExit`` and turn Ctrl-C into a hang;
    the one legitimate pattern (a worker thread delivering the exception
    through a result channel) is allow-marked.

``env-declared``
    Every ``TPUDASH_*`` name referenced anywhere in the package must be
    declared in the config registry AND documented in
    ``docs/OPERATIONS.md``.  A knob that exists only in the code that
    reads it is invisible to operators.

Allow mechanism
---------------
``# tpulint: allow[rule]`` or ``# tpulint: allow[rule-a,rule-b] reason``
suppresses those rules on that line, on the line below the marker when it
stands alone, or — when placed on a ``def``/``with`` header — throughout
that block (for ``blocking-under-lock``, whose findings are scoped, not
pointwise).  There is no file-level or global suppression on purpose:
every exception is a visible, reasoned, line-anchored decision.

Usage::

    python -m tpudash.analysis.lint              # lint the package
    python -m tpudash.analysis.lint path/ f.py   # lint specific trees
    python -m tpudash.analysis.lint --rules      # list the rules

Exit status 0 = clean; 1 = findings (printed as ``file:line: rule:
message``); 2 = usage/internal error.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys

RULE_WALL_CLOCK = "wall-clock"
RULE_ENV_READ = "env-read"
RULE_BLOCKING = "blocking-under-lock"
RULE_BROAD_EXCEPT = "broad-except"
RULE_ENV_DECLARED = "env-declared"

ALL_RULES = (
    RULE_WALL_CLOCK,
    RULE_ENV_READ,
    RULE_BLOCKING,
    RULE_BROAD_EXCEPT,
    RULE_ENV_DECLARED,
)

RULE_DOCS = {
    RULE_WALL_CLOCK: (
        "time.time() requires an explicit allow marker; deadline/backoff/"
        "breaker/cadence arithmetic must use time.monotonic()"
    ),
    RULE_ENV_READ: (
        "TPUDASH_* environment reads are allowed only in tpudash/config.py "
        "(route through the Config registry / env_read helper)"
    ),
    RULE_BLOCKING: (
        "no blocking calls (requests.*, time.sleep, file I/O, sockets, "
        "subprocesses) while a threading lock is held"
    ),
    RULE_BROAD_EXCEPT: (
        "no bare except:, and except BaseException must re-raise "
        "(or carry an allow marker explaining the delivery channel)"
    ),
    RULE_ENV_DECLARED: (
        "every referenced TPUDASH_* var must be declared in the config "
        "registry and documented in docs/OPERATIONS.md"
    ),
}

_ENV_TOKEN = re.compile(r"TPUDASH_[A-Z0-9_]+")
_ALLOW = re.compile(r"#\s*tpulint:\s*allow\[([a-z\-,\s]+)\]")

#: call roots (module aliases resolved per file) whose invocation blocks:
#: HTTP, sockets, subprocesses, filesystem mutation, archive/np disk I/O
_BLOCKING_ROOTS = {
    "requests",
    "urllib",
    "socket",
    "subprocess",
    "shutil",
}
#: os.<attr> calls that hit the filesystem
_BLOCKING_OS_ATTRS = {
    "fdopen",
    "replace",
    "rename",
    "remove",
    "unlink",
    "makedirs",
    "mkdir",
    "rmdir",
}
#: numpy disk round-trips (np.save/np.load under a lock is a real hazard
#: here: history snapshots compress for ~100ms)
_BLOCKING_NP_ATTRS = {"save", "savez", "savez_compressed", "load"}


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def _parse_allows(source: str) -> dict[int, set[str]]:
    """line number (1-based) → set of rule names allowed on that line."""
    allows: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _ALLOW.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        allows.setdefault(i, set()).update(rules)
        # a marker on its own line covers the line below it
        if text.lstrip().startswith("#"):
            allows.setdefault(i + 1, set()).update(rules)
    return allows


def _dotted(node: ast.AST) -> list[str] | None:
    """``a.b.c`` → ["a", "b", "c"]; None for anything non-name-rooted."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _FileChecker(ast.NodeVisitor):
    """One file's worth of rule evaluation (imports resolved per file)."""

    def __init__(
        self,
        path: str,
        source: str,
        is_config: bool,
        declared_env: "frozenset[str] | None",
    ):
        self.path = path
        self.is_config = is_config
        self.declared_env = declared_env
        self.allows = _parse_allows(source)
        self.findings: list[Finding] = []
        self.env_tokens: set[tuple[str, int]] = set()  # (name, line)
        # module alias tables, filled by import visitors (function-local
        # imports included: the visitor walks the whole tree)
        self.time_aliases: set[str] = set()
        self.time_time_names: set[str] = set()
        self.time_sleep_names: set[str] = set()
        self.os_aliases: set[str] = set()
        self.environ_names: set[str] = set()
        self.getenv_names: set[str] = set()
        self.np_aliases: set[str] = set()
        self.blocking_roots: set[str] = set()
        #: stack of (kind, header_line) lock scopes currently open;
        #: non-empty means "a threading lock is (lexically) held here"
        self._lock_scopes: list[int] = []

    # -- plumbing ------------------------------------------------------------
    def _allowed(self, rule: str, line: int) -> bool:
        if rule in self.allows.get(line, ()):
            return True
        # scoped allow: a marker on an enclosing with/def header
        return any(
            rule in self.allows.get(scope_line, ())
            for scope_line in self._lock_scopes
        )

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if not self._allowed(rule, line):
            self.findings.append(Finding(self.path, line, rule, message))

    # -- imports -------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            top = alias.name.split(".")[0]
            bound = alias.asname or top
            if alias.name == "time" or (
                alias.asname and top == "time"
            ):
                self.time_aliases.add(bound)
            if top == "os":
                self.os_aliases.add(bound)
            if top in ("numpy",):
                self.np_aliases.add(bound)
            if top in _BLOCKING_ROOTS:
                self.blocking_roots.add(bound)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "time":
                    self.time_time_names.add(bound)
                if alias.name == "sleep":
                    self.time_sleep_names.add(bound)
        if node.module == "os":
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "environ":
                    self.environ_names.add(bound)
                if alias.name == "getenv":
                    self.getenv_names.add(bound)
        self.generic_visit(node)

    # -- scope tracking ------------------------------------------------------
    def _is_lockish(self, expr: ast.AST) -> bool:
        """Heuristic: the with-item looks like acquiring a threading lock
        (final name segment contains "lock": ``self._publish_lock``,
        ``with lock:``, ``self._history_save_lock``)."""
        parts = _dotted(expr)
        if parts is None:
            return False
        return "lock" in parts[-1].lower()

    def _visit_with(self, node) -> None:
        lockish = any(self._is_lockish(item.context_expr) for item in node.items)
        if lockish:
            self._lock_scopes.append(node.lineno)
        self.generic_visit(node)
        if lockish:
            self._lock_scopes.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _visit_funcdef(self, node) -> None:
        # a nested function's body does not run under the enclosing lock;
        # conversely, *_locked functions run under their caller's lock by
        # project convention
        saved = self._lock_scopes
        self._lock_scopes = [node.lineno] if node.name.endswith("_locked") else []
        self.generic_visit(node)
        self._lock_scopes = saved

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self._lock_scopes
        self._lock_scopes = []
        self.generic_visit(node)
        self._lock_scopes = saved

    # -- rule: broad-except --------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._flag(
                RULE_BROAD_EXCEPT,
                node,
                "bare 'except:' catches BaseException (KeyboardInterrupt, "
                "SystemExit); name the exception or re-raise",
            )
        else:
            parts = _dotted(node.type)
            if parts and parts[-1] == "BaseException":
                reraises = any(
                    isinstance(n, ast.Raise) for n in ast.walk(node)
                )
                if not reraises:
                    self._flag(
                        RULE_BROAD_EXCEPT,
                        node,
                        "'except BaseException' without re-raise swallows "
                        "KeyboardInterrupt/SystemExit",
                    )
        self.generic_visit(node)

    # -- rule: env tokens (collection for env-declared) ----------------------
    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            for name in _ENV_TOKEN.findall(node.value):
                self.env_tokens.add((name, node.lineno))

    # -- calls / subscripts / membership -------------------------------------
    def _env_literal(self, node: ast.AST) -> str | None:
        s = _str_const(node)
        if s is not None and _ENV_TOKEN.fullmatch(s):
            return s
        return None

    def visit_Call(self, node: ast.Call) -> None:
        parts = _dotted(node.func)

        # wall-clock: time.time() / time() (from-import)
        if parts is not None:
            if (
                len(parts) == 2
                and parts[0] in self.time_aliases
                and parts[1] == "time"
            ) or (len(parts) == 1 and parts[0] in self.time_time_names):
                self._flag(
                    RULE_WALL_CLOCK,
                    node,
                    "time.time() in code: use time.monotonic() for "
                    "deadline/backoff/cadence arithmetic, or mark the site "
                    "# tpulint: allow[wall-clock] <why wall-clock semantics "
                    "are intended>",
                )

        # env-read: os.environ.get("TPUDASH_*"), os.getenv("TPUDASH_*"),
        # and any mapping.get("TPUDASH_*") — an env dict passed around
        # under another name is still an env read
        if not self.is_config and node.args:
            lit = self._env_literal(node.args[0])
            if lit is not None and parts is not None:
                is_get_method = parts[-1] == "get"
                is_getenv = (
                    len(parts) == 2
                    and parts[0] in self.os_aliases
                    and parts[1] == "getenv"
                ) or (len(parts) == 1 and parts[0] in self.getenv_names)
                if is_get_method or is_getenv:
                    self._flag(
                        RULE_ENV_READ,
                        node,
                        f"direct read of {lit} outside tpudash/config.py — "
                        "declare it in the registry and use "
                        "tpudash.config.env_read/env_is_set",
                    )

        # blocking-under-lock
        if self._lock_scopes and parts is not None:
            blocked: str | None = None
            if len(parts) == 1 and parts[0] == "open":
                blocked = "open() file I/O"
            elif len(parts) == 1 and parts[0] in self.time_sleep_names:
                blocked = "time.sleep"
            elif len(parts) == 2 and parts[0] in self.time_aliases and parts[1] == "sleep":
                blocked = "time.sleep"
            elif parts[0] in self.blocking_roots:
                blocked = f"{'.'.join(parts)} (network/subprocess/file API)"
            elif (
                len(parts) == 2
                and parts[0] in self.os_aliases
                and parts[1] in _BLOCKING_OS_ATTRS
            ):
                blocked = f"os.{parts[1]} filesystem call"
            elif (
                len(parts) == 2
                and parts[0] in self.np_aliases
                and parts[1] in _BLOCKING_NP_ATTRS
            ):
                blocked = f"numpy {parts[1]} disk I/O"
            if blocked is not None:
                self._flag(
                    RULE_BLOCKING,
                    node,
                    f"{blocked} while a threading lock is held (scope opened "
                    f"at line {self._lock_scopes[-1]}) stalls every waiter; "
                    "move it outside the lock or mark the dedicated-I/O-lock "
                    "scope with # tpulint: allow[blocking-under-lock]",
                )

        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if not self.is_config:
            lit = self._env_literal(node.slice)
            if lit is not None:
                self._flag(
                    RULE_ENV_READ,
                    node,
                    f"subscript read of {lit} outside tpudash/config.py — "
                    "route through the config registry",
                )
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if not self.is_config and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            lit = self._env_literal(node.left)
            if lit is not None:
                self._flag(
                    RULE_ENV_READ,
                    node,
                    f"membership test for {lit} outside tpudash/config.py — "
                    "use tpudash.config.env_is_set",
                )
        self.generic_visit(node)


def _declared_env() -> frozenset[str]:
    from tpudash.config import DECLARED_ENV

    return DECLARED_ENV


def _operations_doc_text() -> str | None:
    """docs/OPERATIONS.md relative to the repo checkout, or None when the
    package runs installed without its docs tree (doc check skipped)."""
    import tpudash

    root = os.path.dirname(os.path.dirname(os.path.abspath(tpudash.__file__)))
    path = os.path.join(root, "docs", "OPERATIONS.md")
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return f.read()


def resolve_cli_paths(
    argv: "list[str]", prog: str
) -> "tuple[list[str] | None, int]":
    """Shared CLI path handling for every analyzer entry point (lint,
    asynccheck, the unified gate): positional args, defaulting to the
    installed tpudash package; loud failure (exit-worthy code in slot 2)
    for a missing path or a path tree with zero Python files — a gate
    that scans nothing "passes" forever, so a typo'd CI path must fail.
    Returns (paths, 0) on success, (None, nonzero-hint) on error; callers
    map the hint onto their own exit-code scheme."""
    paths = [a for a in argv if not a.startswith("-")]
    if not paths:
        import tpudash

        paths = [os.path.dirname(os.path.abspath(tpudash.__file__))]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"{prog}: no such path: {', '.join(missing)}", file=sys.stderr)
        return None, 2
    if not iter_py_files(paths):
        print(
            f"{prog}: no Python files under: {', '.join(paths)}",
            file=sys.stderr,
        )
        return None, 2
    return paths, 0


def iter_py_files(paths: "list[str]") -> "list[str]":
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, f)
                for f in sorted(filenames)
                if f.endswith(".py")
            )
    return sorted(set(out))


def lint_source(
    source: str,
    path: str = "<string>",
    declared_env: "frozenset[str] | None" = None,
    doc_text: "str | None" = None,
) -> list[Finding]:
    """Lint one file's source text (the unit tests' entry point)."""
    is_config = path.replace(os.sep, "/").endswith("tpudash/config.py")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(path, e.lineno or 1, "syntax", f"cannot parse: {e.msg}")
        ]
    checker = _FileChecker(path, source, is_config, declared_env)
    checker.visit(tree)
    findings = checker.findings
    if declared_env is not None:
        for name, line in sorted(checker.env_tokens):
            if name not in declared_env:
                if not checker._allowed(RULE_ENV_DECLARED, line):
                    findings.append(
                        Finding(
                            path,
                            line,
                            RULE_ENV_DECLARED,
                            f"{name} is not declared in the config registry "
                            "(tpudash/config.py _ENV_MAP/_EXTRA_ENV)",
                        )
                    )
            elif doc_text is not None and name not in doc_text:
                if not checker._allowed(RULE_ENV_DECLARED, line):
                    findings.append(
                        Finding(
                            path,
                            line,
                            RULE_ENV_DECLARED,
                            f"{name} is declared but not documented in "
                            "docs/OPERATIONS.md",
                        )
                    )
    return sorted(findings)


def lint_paths(
    paths: "list[str]",
    declared_env: "frozenset[str] | None" = None,
    doc_text: "str | None" = None,
) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding(path, 1, "io", f"cannot read: {e}"))
            continue
        findings.extend(
            lint_source(source, path, declared_env=declared_env, doc_text=doc_text)
        )
    return sorted(findings)


def main(argv: "list[str] | None" = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--rules" in argv:
        for rule in ALL_RULES:
            print(f"{rule}: {RULE_DOCS[rule]}")
        return 0
    paths, err = resolve_cli_paths(argv, "tpulint")
    if paths is None:
        return err
    try:
        declared = _declared_env()
    except Exception as e:  # pragma: no cover - registry import failure
        print(f"tpulint: cannot load config registry: {e}", file=sys.stderr)
        return 2
    doc_text = _operations_doc_text()
    findings = lint_paths(paths, declared_env=declared, doc_text=doc_text)
    for finding in findings:
        print(finding)
    if findings:
        print(
            f"tpulint: {len(findings)} finding"
            f"{'s' if len(findings) != 1 else ''} "
            f"across {len(set(f.path for f in findings))} file(s)",
            file=sys.stderr,
        )
        return 1
    if doc_text is None:
        print(
            "tpulint: clean (docs/OPERATIONS.md not found — "
            "documentation check skipped)"
        )
    else:
        print("tpulint: clean")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
